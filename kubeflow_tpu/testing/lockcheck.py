"""Opt-in runtime lock-ORDER sanitizer (``KFT_LOCKCHECK=1``).

The static lock-guard checker (analysis/locks.py) proves writes hold
the right lock; it cannot see *ordering* — thread A taking
``state._lock`` then ``breaker._lock`` while thread B nests them the
other way deadlocks only under exactly the wrong interleaving, which
no amount of test repetition reliably produces.  This module makes
the ordering observable instead: with the sanitizer installed,
``threading.Lock()`` returns an instrumented lock that

  * tags every lock with its ALLOCATION SITE (file:line) — ordering
    discipline is a property of code sites, not lock instances (all
    ``EndpointState._lock``s are one node);
  * keeps a per-thread stack of held locks and a global site-level
    acquisition graph: acquiring B while holding A adds edge A->B;
  * records a violation whenever a new edge closes a cycle in the
    site graph — the static signature of a potential deadlock, caught
    on the FIRST run that exercises both orders, no interleaving luck
    required.

Violations are recorded, not raised: throwing inside ``acquire``
would corrupt whatever invariant the caller's critical section
protects and turn one report into cascade noise.  The pytest fixture
(tests/conftest.py) enables the sanitizer for the serving/fleet test
modules under ``KFT_LOCKCHECK=1`` and FAILS the test at teardown if
any violation was recorded.

Same-site edges (two ``EndpointState._lock`` instances held at once)
are ignored: instance-level ordering within one site needs an
instance key (e.g. always lock lower id() first) that site granularity
cannot express — flagging them would drown real inversions.

Scope: only locks CREATED while installed are instrumented (the
wrapper replaces the ``threading.Lock`` factory; existing locks are
untouched), so enable it before constructing the objects under test.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional, Set, Tuple

ENV = "KFT_LOCKCHECK"

_real_lock = threading.Lock


def enabled_in_env(environ=os.environ) -> bool:
    return environ.get(ENV, "").strip() not in ("", "0", "false")


class LockOrderViolation:
    """One cycle-closing acquisition, with both paths spelled out."""

    def __init__(self, edge: Tuple[str, str], cycle: List[str],
                 thread: str):
        self.edge = edge
        self.cycle = cycle
        self.thread = thread

    def __repr__(self) -> str:
        path = " -> ".join(self.cycle)
        return (f"lock-order inversion on {self.thread}: acquiring "
                f"{self.edge[1]} while holding {self.edge[0]} closes "
                f"the cycle [{path}]")


class LockOrderSanitizer:
    """The acquisition-graph recorder shared by every checked lock."""

    def __init__(self):
        self._graph_lock = _real_lock()
        # site -> set of sites acquired while this one was held
        self._edges: Dict[str, Set[str]] = {}
        self._violations: List[LockOrderViolation] = []
        self._tls = threading.local()

    # -- called from _CheckedLock ------------------------------------------

    def _held(self) -> List[Tuple[str, int]]:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def note_acquired(self, site: str, ident: int) -> None:
        stack = self._held()
        new_edges = [(held_site, site) for held_site, _ in stack
                     if held_site != site]
        stack.append((site, ident))
        if not new_edges:
            return
        with self._graph_lock:
            for a, b in new_edges:
                if b in self._edges.get(a, ()):
                    continue
                cycle = self._find_path(b, a)
                self._edges.setdefault(a, set()).add(b)
                if cycle is not None:
                    self._violations.append(LockOrderViolation(
                        (a, b), cycle + [b],
                        threading.current_thread().name))

    def note_released(self, site: str, ident: int) -> None:
        stack = self._held()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] == (site, ident):
                del stack[i]
                return

    def _find_path(self, start: str, goal: str) -> Optional[List[str]]:
        """DFS b ~> a in the current edge set — the path that the new
        a->b edge would close into a cycle."""
        seen = set()
        stack = [(start, [start])]
        while stack:
            node, path = stack.pop()
            if node == goal:
                return path
            if node in seen:
                continue
            seen.add(node)
            for nxt in self._edges.get(node, ()):
                stack.append((nxt, path + [nxt]))
        return None

    # -- test surface ------------------------------------------------------

    def violations(self) -> List[LockOrderViolation]:
        with self._graph_lock:
            return list(self._violations)

    def reset(self) -> None:
        with self._graph_lock:
            self._edges.clear()
            self._violations.clear()


class _CheckedLock:
    """Drop-in ``threading.Lock()`` replacement that reports to the
    sanitizer.  Exposes the full lock surface (acquire/release/locked/
    context manager) so Condition and Event internals built on top of
    a patched factory keep working."""

    __slots__ = ("_inner", "_site", "_sanitizer")

    def __init__(self, sanitizer: LockOrderSanitizer, site: str):
        self._inner = _real_lock()
        self._site = site
        self._sanitizer = sanitizer

    def acquire(self, blocking: bool = True,
                timeout: float = -1) -> bool:
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._sanitizer.note_acquired(self._site, id(self))
        return got

    def release(self) -> None:
        self._sanitizer.note_released(self._site, id(self))
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    # Condition() probes these on its lock; delegating keeps a
    # checked lock usable as Condition backing storage.
    def _at_fork_reinit(self) -> None:
        self._inner._at_fork_reinit()


_active: Optional[LockOrderSanitizer] = None


def active() -> Optional[LockOrderSanitizer]:
    return _active


def install() -> LockOrderSanitizer:
    """Swap ``threading.Lock`` for the checked factory.  Returns the
    sanitizer; idempotent (a second install returns the live one)."""
    global _active
    if _active is not None:
        return _active
    sanitizer = LockOrderSanitizer()

    def make_lock():
        import sys

        frame = sys._getframe(1)
        site = f"{frame.f_code.co_filename.rsplit('/', 1)[-1]}:" \
               f"{frame.f_lineno}"
        return _CheckedLock(sanitizer, site)

    threading.Lock = make_lock
    _active = sanitizer
    return sanitizer


def uninstall() -> None:
    global _active
    threading.Lock = _real_lock
    _active = None
