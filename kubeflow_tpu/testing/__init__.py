"""E2E test harness: drivers, JUnit artifacts, Argo-style DAG renderer."""
