"""JUnit XML result emission — heir of the reference's wrap_test
(testing/test_deploy.py:253-276), which wrapped each E2E step's outcome
into JUnit artifacts for TestGrid/Gubernator.
"""

from __future__ import annotations

import dataclasses
import time
import traceback
from pathlib import Path
from typing import Callable, List, Optional
from xml.sax.saxutils import escape


@dataclasses.dataclass
class TestCase:
    name: str
    time_s: float = 0.0
    failure: Optional[str] = None
    error: Optional[str] = None

    @property
    def ok(self) -> bool:
        return self.failure is None and self.error is None


class JUnitSuite:
    """Collects cases; writes junit_<name>.xml like the reference's
    artifact convention."""

    def __init__(self, name: str):
        self.name = name
        self.cases: List[TestCase] = []

    def run(self, case_name: str, fn: Callable[[], None]) -> TestCase:
        """Run fn, recording wall time and failure/error classification
        (AssertionError -> <failure>, anything else -> <error>)."""
        t0 = time.monotonic()
        case = TestCase(name=case_name)
        try:
            fn()
        except AssertionError:
            case.failure = traceback.format_exc()
        except Exception:
            case.error = traceback.format_exc()
        case.time_s = time.monotonic() - t0
        self.cases.append(case)
        return case

    @property
    def ok(self) -> bool:
        return all(c.ok for c in self.cases)

    def to_xml(self) -> str:
        failures = sum(1 for c in self.cases if c.failure)
        errors = sum(1 for c in self.cases if c.error)
        total_time = sum(c.time_s for c in self.cases)
        lines = [
            '<?xml version="1.0" encoding="utf-8"?>',
            f'<testsuite name="{escape(self.name)}" tests="{len(self.cases)}"'
            f' failures="{failures}" errors="{errors}"'
            f' time="{total_time:.3f}">',
        ]
        for c in self.cases:
            lines.append(
                f'  <testcase name="{escape(c.name)}" time="{c.time_s:.3f}"'
                + ("/>" if c.ok else ">")
            )
            if c.failure is not None:
                lines.append(
                    f'    <failure>{escape(c.failure)}</failure>')
            if c.error is not None:
                lines.append(f'    <error>{escape(c.error)}</error>')
            if not c.ok:
                lines.append("  </testcase>")
        lines.append("</testsuite>")
        return "\n".join(lines)

    def write(self, artifacts_dir: str | Path) -> Path:
        out = Path(artifacts_dir)
        out.mkdir(parents=True, exist_ok=True)
        path = out / f"junit_{self.name}.xml"
        path.write_text(self.to_xml())
        return path
