"""A real-HTTP Kubernetes API server emulation for hermetic E2E tests.

The reference could only test its operators against rented clusters
(SURVEY.md §4: per-run GCE VMs); this module brings the missing piece
in-process: a ``ThreadingHTTPServer`` that speaks the slice of the
Kubernetes REST contract the framework uses — pods, services, nodes,
the TPUJob custom resource (+ /status merge-patch), events, label
selectors, and the 404/409 error shapes — backed by the same FakeKube
store the unit tests drive directly.

With it, ``operator/kube_http.py`` (the stdlib HTTP backend) and the
whole reconcile loop run over REAL sockets: URL construction, selector
encoding, patch content types, and error mapping are integration-tested
without a cluster.  The FakeKube store doubles as the test's state
handle (flip pod phases, read events) exactly as in the in-memory
tests.
"""

from __future__ import annotations

import json
import re
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple

from kubeflow_tpu.operator import crd
from kubeflow_tpu.operator.kube import Conflict, FakeKube, NotFound

_POD = re.compile(r"^/api/v1/namespaces/(?P<ns>[^/]+)/pods(?:/(?P<name>[^/]+))?$")
_SVC = re.compile(
    r"^/api/v1/namespaces/(?P<ns>[^/]+)/services(?:/(?P<name>[^/]+))?$")
_EVT = re.compile(r"^/api/v1/namespaces/(?P<ns>[^/]+)/events$")
_DEP = re.compile(
    r"^/apis/apps/v1/namespaces/(?P<ns>[^/]+)/deployments"
    r"(?:/(?P<name>[^/]+))?$")
_NODES = re.compile(r"^/api/v1/nodes$")
_CR = re.compile(
    rf"^/apis/{re.escape(crd.GROUP)}/{crd.VERSION}"
    r"(?:/namespaces/(?P<ns>[^/]+))?"
    rf"/{crd.PLURAL}(?:/(?P<name>[^/]+))?(?P<status>/status)?$")


def _parse_selector(qs: str) -> Optional[dict]:
    params = urllib.parse.parse_qs(qs)
    sel = params.get("labelSelector", [""])[0]
    if not sel:
        return None
    out = {}
    for clause in sel.split(","):
        k, _, v = clause.partition("=")
        out[k] = v
    return out


class _Handler(BaseHTTPRequestHandler):
    kube: FakeKube  # set by make_fake_apiserver
    fail_queue: list  # injected failure codes; set by make_fake_apiserver

    def log_message(self, fmt, *args):
        pass

    # -- plumbing ---------------------------------------------------------

    def _body(self) -> dict:
        n = int(self.headers.get("Content-Length", 0))
        return json.loads(self.rfile.read(n)) if n else {}

    def _send(self, code: int, payload=None, headers=None) -> None:
        data = json.dumps(payload if payload is not None else {}).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(data)

    def _dispatch(self, method: str) -> None:
        # Injected-failure queue (httpd.fail_queue): each entry is an
        # HTTP status code — or a (code, retry_after_s) pair, served
        # with a Retry-After header — handed verbatim to one request,
        # before any routing; how the retry layer in
        # operator/kube_http.py is integration-tested against real
        # 5xx/429 weather (and its backoff-hint honoring) over sockets.
        if self.fail_queue:
            try:
                code = self.fail_queue.pop(0)
            except IndexError:
                code = None  # raced another handler thread; serve real
            if code is not None:
                headers = None
                if isinstance(code, tuple):
                    code, retry_after = code
                    headers = {"Retry-After": str(retry_after)}
                self._send(int(code), {
                    "kind": "Status", "code": int(code),
                    "message": "injected failure"}, headers=headers)
                return
        path, _, qs = self.path.partition("?")
        try:
            handled = self._route(method, path, qs)
        except NotFound as e:
            self._send(404, {"kind": "Status", "code": 404,
                             "message": str(e)})
            return
        except Conflict as e:
            self._send(409, {"kind": "Status", "code": 409,
                             "message": str(e)})
            return
        if not handled:
            self._send(404, {"kind": "Status", "code": 404,
                             "message": f"no route {method} {path}"})

    # -- routes -----------------------------------------------------------

    def _route(self, method: str, path: str, qs: str) -> bool:
        kube = self.kube

        m = _NODES.match(path)
        if m and method == "GET":
            self._send(200, {"items": kube.list_nodes()})
            return True

        m = _EVT.match(path)
        if m and method == "POST":
            body = self._body()
            kube.record_event(
                m["ns"],
                f"{body.get('involvedObject', {}).get('kind', '?')}/"
                f"{body.get('involvedObject', {}).get('name', '?')}",
                body.get("reason", ""), body.get("message", ""),
                body.get("type", "Normal"))
            self._send(201, body)
            return True

        m = _POD.match(path)
        if m:
            ns, name = m["ns"], m["name"]
            if method == "POST" and not name:
                self._send(201, kube.create_pod(self._body()))
                return True
            if method == "GET" and name:
                self._send(200, kube.get_pod(ns, name))
                return True
            if method == "GET":
                self._send(200, {"items": kube.list_pods(
                    ns, _parse_selector(qs))})
                return True
            if method == "DELETE" and name:
                kube.delete_pod(ns, name)
                self._send(200)
                return True

        m = _SVC.match(path)
        if m:
            ns, name = m["ns"], m["name"]
            if method == "POST" and not name:
                self._send(201, kube.create_service(self._body()))
                return True
            if method == "DELETE" and name:
                kube.delete_service(ns, name)
                self._send(200)
                return True

        m = _DEP.match(path)
        if m:
            ns, name = m["ns"], m["name"]
            if method == "POST" and not name:
                self._send(201, kube.create_deployment(self._body()))
                return True
            if method == "GET" and name:
                self._send(200, kube.get_deployment(ns, name))
                return True
            if method == "GET":
                self._send(200, {"items": kube.list_deployments(
                    ns, _parse_selector(qs))})
                return True
            if method == "PATCH" and name:
                # Scale patches ride the deployment object itself as a
                # merge-patch {"spec": {"replicas": N}} — same content
                # type discipline as the CR /status subresource.
                if self.headers.get("Content-Type") != \
                        "application/merge-patch+json":
                    self._send(415, {"message": "merge-patch required"})
                    return True
                replicas = self._body().get("spec", {}).get("replicas")
                if replicas is None:
                    self._send(422, {"message": "spec.replicas required"})
                    return True
                self._send(200, kube.patch_deployment_scale(
                    ns, name, int(replicas)))
                return True

        m = _CR.match(path)
        if m:
            ns, name, status = m["ns"], m["name"], m["status"]
            if method == "POST" and not name:
                self._send(201, kube.create_custom(self._body()))
                return True
            if method == "GET" and name and not status:
                self._send(200, kube.get_custom(ns, name))
                return True
            if method == "GET" and not name:
                self._send(200, {"items": kube.list_custom(ns)})
                return True
            if method == "PATCH" and name and status:
                if self.headers.get("Content-Type") != \
                        "application/merge-patch+json":
                    self._send(415, {"message": "merge-patch required"})
                    return True
                kube.update_custom_status(
                    ns, name, self._body().get("status", {}))
                self._send(200)
                return True
            if method == "DELETE" and name and not status:
                # Existence check through the store's own locked
                # accessor (raises NotFound): iterating kube.custom here
                # would race concurrent handler threads.
                kube.get_custom(ns, name)
                kube.delete_custom(ns, name)
                self._send(200)
                return True
        return False

    def do_GET(self):
        self._dispatch("GET")

    def do_POST(self):
        self._dispatch("POST")

    def do_PATCH(self):
        self._dispatch("PATCH")

    def do_DELETE(self):
        self._dispatch("DELETE")


def make_fake_apiserver(
    kube: Optional[FakeKube] = None, port: int = 0,
) -> Tuple[ThreadingHTTPServer, threading.Thread, FakeKube]:
    """Start the emulated API server on localhost.

    Returns (httpd, thread, store): ``store`` is the backing FakeKube —
    drive pod phases / read events through it while clients talk HTTP.
    ``httpd.fail_queue`` is the injected-failure queue: append HTTP
    status codes and the server serves each to exactly one upcoming
    request (any route) before handling resumes — apiserver weather on
    demand for retry/backoff tests.
    """
    store = kube or FakeKube()

    class Handler(_Handler):
        pass

    Handler.kube = store
    Handler.fail_queue = []
    httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
    httpd.fail_queue = Handler.fail_queue
    thread = threading.Thread(target=httpd.serve_forever, daemon=True,
                              name="fake-apiserver")
    thread.start()
    return httpd, thread, store
