"""Deterministic fault-injection harness for the serving/operator planes.

The reference stack's failure paths were exercised only by real cluster
weather (SURVEY.md §4); ours are driven deterministically: named hook
sites in production code call :func:`fire`, which is a no-op until a
:class:`FaultInjector` is installed — from a test, or from the
``KFT_FAULTS`` env var at process start (serving/main.py installs it),
so the same scripted chaos runs in-process, in the e2e harness, and
against a deployed container.

Hook sites planted in production code (grep for ``faults.fire``):

    engine.step       before each DecodeEngine step-program call
                      (sleep = slow/wedged step, raise = device death)
    engine.admit      before each prefill admission call
    engine.alloc_block before paged-KV pages are taken from a slot's
                      admission reservation as its frontier grows
                      (sleep = slow allocator under pool pressure,
                      raise = allocation failure — engine death at
                      the growth site, every waiter resolved)
    batcher.dispatch  MicroBatcher batch dispatch (sleep = queue stall)
    loader.load       ModelServer.reload before load_version
                      (raise = corrupt checkpoint directory)
    kube.request      HttpKube transport attempt (raise = apiserver
                      connection failure, before the retry layer)
    router.forward    fleet router upstream attempt (raise = replica
                      connection failure, before the socket — the
                      retry/ejection layer sees it as a refused
                      connect)
    router.replay     each replay/failover attempt the router grants
                      for an idempotent POST — after the cap and the
                      retry-budget withdrawal, before the new replica
                      is picked (raise = failure of the failover path
                      itself; the chaos e2e's deterministic replay
                      observation point)
    engine.resume     DecodeEngine admission of a resume request
                      (prompt + tokens a prior attempt delivered,
                      the router's mid-generation failover payload;
                      sleep = slow failover, raise = resume rejected)
    engine.kv_handoff disaggregated prefill/decode page transfer —
                      fired on the prefill tier's export gather and
                      the decode tier's import scatter (sleep = slow
                      cross-replica transfer, raise = handoff
                      failure; the router surfaces it rather than
                      hanging the tiered dispatch)
    router.tier_dispatch
                      the router's tiered prefill-then-decode
                      dispatch decision for a :generate (raise =
                      tier routing failure — the request must fall
                      back to the untiered path, never hang or 500)
    engine.spill      hierarchical-KV host-tier traffic: the spill-out
                      gather (raise = spill abandoned, the record
                      stays device-resident and destructive eviction
                      remains the fallback), the park gather (raise =
                      the session parks device-resident only), and
                      the spill-in re-import at admission (raise =
                      typed Overloaded shed, no page leaked in
                      either tier; sleep = slow host copy)
    engine.fetch      the :fetch_kv host-tier read a failover peer
                      asks for a session's pages (raise = fetch
                      failure — the router falls back to
                      recompute-resume, sleep = slow fetch)
    adapter.load      AdapterRegistry cold-load of a requested
                      adapter from disk, before the artifact read
                      (raise = corrupt/missing adapter: the request
                      sheds 404, the breaker opens, and resident
                      last-good adapters KEEP serving; sleep = slow
                      hot-load under traffic)
    adapter.evict     LRU eviction of an idle resident adapter to
                      free a slot (raise = eviction failure — the
                      incoming load sheds, nothing in-flight is
                      touched)
    fleet.probe       endpoint registry readiness probe attempt
    scheduler.admit   cluster scheduler admission-plan pass (skew =
                      age the queue / expire preemption windows,
                      raise = wedged policy pass — the reconcile
                      error path must contain it)
    scheduler.preempt each eviction wave the policy commits (before
                      victims are marked)
    scheduler.fuse    each fused gang the fold pass forms from
                      fusable queued singletons (scheduler/fuse.py;
                      raise = wedged fold — contained like a wedged
                      admission pass, members stay queued singletons)
    scheduler.colocate
                      each serving-claim view the colocation fold
                      splits or admits into the shared pool
                      (scheduler/colocate.py; raise = wedged fold —
                      contained, the claim stays pending and training
                      is untouched)
    autoscaler.claim  each ServingClaimClient.sync of the desired
                      replica count into the claim CR (raise =
                      apiserver blip — the autoscaler loop absorbs it
                      and the next level-triggered pass repairs;
                      sleep = slow claim write)
    train.step        each Trainer.fit loop iteration, before the
                      dispatch (raise = step fault the supervisor
                      restarts from, skew = ages stall/backoff
                      deadlines)
    checkpoint.save   background checkpoint finalize, between the
                      orbax commit and the manifest write (raise =
                      kill mid-save: step left unverified, error
                      surfaces at the next save()/wait())
    checkpoint.restore each CheckpointManager.restore attempt
    data.next         each TensorBatches batch pull (raise = one
                      transient read error, retried with backoff)

Clock skips: deadline/backoff code reads :func:`monotonic` instead of
``time.monotonic`` — a ``skew`` action (or ``advance_clock`` from a
test) jumps that clock forward so deadline expiry and circuit-breaker
cool-downs are tested in microseconds of wall time.  Perf timings keep
using the real clock; only *policy* clocks are skewable.

Spec grammar (``KFT_FAULTS``), ``;``-separated entries::

    seed=N                          RNG seed for @prob draws (default 0)
    site:action[=value][*times][@prob]

    engine.step:sleep=0.05*3        first 3 steps take +50 ms
    loader.load:raise               every reload attempt raises
    batcher.dispatch:stall=0.2@0.5  ~half of dispatches stall 200 ms
    engine.step:skew=5*1            one step jumps the policy clock 5 s

Actions: ``raise`` (FaultInjected), ``sleep``/``stall`` (block value
seconds), ``skew`` (advance the policy clock value seconds).  ``*times``
bounds firings (default unlimited); ``@prob`` fires each encounter with
that probability from the seeded RNG — the whole scenario is a pure
function of the spec string, so a chaos run is replayable.
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import random
import threading
import time
from typing import Dict, List, Optional

ENV = "KFT_FAULTS"


class FaultInjected(RuntimeError):
    """The scripted failure a ``raise`` action throws at its hook site."""


@dataclasses.dataclass
class FaultSpec:
    site: str
    action: str            # raise | sleep | stall | skew
    value: float = 0.0
    times: int = -1        # firings remaining; -1 = unlimited
    prob: float = 1.0

    _ACTIONS = ("raise", "sleep", "stall", "skew")

    def __post_init__(self):
        if self.action not in self._ACTIONS:
            raise ValueError(
                f"unknown fault action {self.action!r} for site "
                f"{self.site!r}; known: {self._ACTIONS}")


def parse(spec: str) -> "FaultInjector":
    """Parse a ``KFT_FAULTS`` string into an injector (see grammar)."""
    seed = 0
    specs: List[FaultSpec] = []
    for raw in spec.split(";"):
        entry = raw.strip()
        if not entry:
            continue
        if entry.startswith("seed="):
            seed = int(entry[5:])
            continue
        site, sep, rest = entry.partition(":")
        if not sep or not rest:
            raise ValueError(
                f"bad fault entry {entry!r}: want site:action[=value]"
                f"[*times][@prob]")
        prob = 1.0
        if "@" in rest:
            rest, _, p = rest.rpartition("@")
            prob = float(p)
        times = -1
        if "*" in rest:
            rest, _, t = rest.rpartition("*")
            times = int(t)
        action, _, value = rest.partition("=")
        specs.append(FaultSpec(site=site, action=action,
                               value=float(value) if value else 0.0,
                               times=times, prob=prob))
    return FaultInjector(specs, seed=seed)


class FaultInjector:
    """Seeded, scripted fault firing at named hook sites.

    Thread-safe: hook sites fire from server/dispatch/loop threads while
    tests read counts.  The RNG and remaining-times bookkeeping live
    under one lock; the sleep itself runs outside it (a stalled dispatch
    must not stall every other site)."""

    def __init__(self, specs: List[FaultSpec], seed: int = 0):
        self._lock = threading.Lock()
        self._specs: Dict[str, List[FaultSpec]] = {}
        for s in specs:
            self._specs.setdefault(s.site, []).append(
                dataclasses.replace(s))
        self._rng = random.Random(seed)
        self._fired: Dict[str, int] = {}
        self._skew = 0.0

    # -- hook-site surface -------------------------------------------------

    def fire(self, site: str) -> None:
        """Run the scripted actions for one encounter of ``site``.

        Every encounter is COUNTED (fired()), with or without a spec at
        the site — tests use the count to prove production code did or
        did NOT reach a hook (e.g. the reload breaker skipping the
        loader entirely while open)."""
        sleep_s = 0.0
        boom: Optional[FaultInjected] = None
        with self._lock:
            self._fired[site] = self._fired.get(site, 0) + 1
            for s in self._specs.get(site, ()):
                if s.times == 0:
                    continue
                if s.prob < 1.0 and self._rng.random() >= s.prob:
                    continue
                if s.times > 0:
                    s.times -= 1
                if s.action in ("sleep", "stall"):
                    sleep_s += s.value
                elif s.action == "skew":
                    self._skew += s.value
                elif boom is None:
                    boom = FaultInjected(
                        f"injected fault at {site}")
        if sleep_s:
            time.sleep(sleep_s)
        if boom is not None:
            raise boom

    def monotonic(self) -> float:
        """The policy clock: real monotonic time plus accumulated skew."""
        with self._lock:
            return time.monotonic() + self._skew

    # -- test surface ------------------------------------------------------

    def advance_clock(self, seconds: float) -> None:
        """Jump the policy clock forward (deadlines/backoffs expire)."""
        with self._lock:
            self._skew += float(seconds)

    def fired(self, site: str) -> int:
        """Hook-site ENCOUNTERS while this injector was installed (a
        site with no spec still counts — see fire())."""
        with self._lock:
            return self._fired.get(site, 0)


# The installed injector.  Hook sites read the module global once per
# encounter — when nothing is installed the cost is one attribute load
# and an ``is None`` branch, cheap enough for the engine step loop.
_ACTIVE: Optional[FaultInjector] = None


def active() -> Optional[FaultInjector]:
    return _ACTIVE


def fire(site: str) -> None:
    inj = _ACTIVE
    if inj is not None:
        inj.fire(site)


def monotonic() -> float:
    """Policy clock for deadline and backoff decisions (skewable)."""
    inj = _ACTIVE
    return inj.monotonic() if inj is not None else time.monotonic()


def policy_backoff(attempt: int, base_s: float, cap_s: float,
                   rng: random.Random, poll_s: float = 0.05) -> None:
    """The repo's one capped-jittered retry backoff, expired on the
    POLICY clock: delay = min(base * 2^(attempt-1), cap) jittered to
    [0.8, 1.2]x, waited by polling :func:`monotonic` in short wall
    sleeps — a seeded ``skew`` (or ``advance_clock``) expires it in
    microseconds of wall time.  Shared by the training supervisor's
    restart backoff and the data loader's transient-read retry."""
    base = min(base_s * (2 ** (max(attempt, 1) - 1)), cap_s)
    delay = base * (0.8 + 0.4 * rng.random())
    deadline = monotonic() + delay
    while monotonic() < deadline:
        time.sleep(min(poll_s, max(0.0, delay)))


def install(injector: Optional[FaultInjector]) -> None:
    global _ACTIVE
    _ACTIVE = injector


def install_from_env(environ=os.environ) -> Optional[FaultInjector]:
    """Install the ``KFT_FAULTS`` scenario, if any (serving/main.py
    calls this at startup so deployed containers honor the env var)."""
    spec = environ.get(ENV, "").strip()
    if not spec:
        return None
    inj = parse(spec)
    install(inj)
    return inj


@contextlib.contextmanager
def injected(spec: str):
    """Test-scoped installation: ``with faults.injected("site:raise"):``
    installs the parsed scenario and restores the previous injector on
    exit (exception-safe; scenarios must not leak across tests)."""
    prev = _ACTIVE
    inj = parse(spec)
    install(inj)
    try:
        yield inj
    finally:
        install(prev)
