#!/bin/bash
# Round-4 on-chip sweep, gated on chip availability: the tunneled chip's
# grant wedged mid-round (see BASELINE.md "measurement debt"); this
# probes every 10 min and runs the queued sweeps the moment it clears.
cd /root/repo
LOG=/root/repo/artifacts/r4_onchip_sweeps.log
: > "$LOG"
echo "waiter started $(date +%H:%M:%S)" >> "$LOG"
for i in $(seq 1 50); do
  if timeout 120 python -c "
import bench
def get():
    import jax
    return jax.devices()
devs, fail = bench.acquire_devices(get, attempts=1, attempt_timeout_s=90,
                                   log=lambda m: None)
raise SystemExit(0 if devs else 1)
" 2>/dev/null; then
    echo "chip OK at $(date +%H:%M:%S); starting sweeps" >> "$LOG"
    break
  fi
  echo "probe $i: wedged $(date +%H:%M:%S)" >> "$LOG"
  sleep 600
done

run() {
  desc="$1"; shift
  echo "=== $desc $(date +%H:%M:%S)" >> "$LOG"
  timeout 900 python bench.py "$@" 2>>/tmp/sweep_stderr.log \
    | python -c "
import json, sys
try:
    d = json.load(sys.stdin)
except Exception as e:
    print('PARSE-FAIL', e)
else:
    det = d.get('detail', {})
    print('RESULT', '$desc', d['value'], d['unit'],
          'step_ms', det.get('step_time_ms'), 'mfu', det.get('mfu'),
          'mixed_req_s', det.get('batcher_mixed_requests_per_sec'),
          'mixed_mb', det.get('batcher_mixed_mean_batch_size'),
          'uniform_req_s', det.get('batcher_requests_per_sec'))
" >> "$LOG"
}

run ce-f32       --model=lm --steps 60 --ce-dtype f32
run ce-compute   --model=lm --steps 60 --ce-dtype compute
run ce-f32-b     --model=lm --steps 60 --ce-dtype f32
run ce-compute-b --model=lm --steps 60 --ce-dtype compute
run moe-gather   --model=lm --steps 60 --moe-experts 4 --moe-impl gather
run moe-einsum   --model=lm --steps 60 --moe-experts 4 --moe-impl einsum
run moe-gather-b --model=lm --steps 60 --moe-experts 4 --moe-impl gather
run moe-einsum-b --model=lm --steps 60 --moe-experts 4 --moe-impl einsum
run lm-decode    --model=lm-decode
echo "SWEEP_DONE $(date +%H:%M:%S)" >> "$LOG"
