"""End-to-end SPMD training tests on the fake slice.

The reference's only training test was an E2E TFJob on a rented cluster
(SURVEY.md §4 tier 4); here the equivalent signal — 'a model trains,
sharded, and checkpoints survive' — runs hermetically on the CPU mesh.
"""

import numpy as np
import jax
import jax.numpy as jnp
import optax
import pytest

from kubeflow_tpu.models.classification import classification_task, eval_step
from kubeflow_tpu.models.resnet import ResNet18, ResNetConfig
from kubeflow_tpu.parallel import MeshSpec
from kubeflow_tpu.runtime.checkpoint import CheckpointManager
from kubeflow_tpu.runtime.metrics import MetricsLogger
from kubeflow_tpu.runtime.train import Trainer


BATCH, IMG, CLASSES = 16, 32, 4


def fake_data(seed=0):
    rng = np.random.RandomState(seed)
    while True:
        labels = rng.randint(0, CLASSES, size=(BATCH,))
        # Label-dependent mean so the model has signal to learn.
        images = rng.randn(BATCH, IMG, IMG, 3).astype(np.float32)
        images += labels[:, None, None, None] * 0.5
        yield {"image": images, "label": labels}


@pytest.fixture(scope="module")
def trainer(devices):
    mesh = MeshSpec(data=8).build(devices)
    model = ResNet18(num_classes=CLASSES, num_filters=8)
    init_fn, loss_fn = classification_task(model, (1, IMG, IMG, 3))
    return Trainer(
        init_fn=init_fn,
        loss_fn=loss_fn,
        tx=optax.adam(1e-3),
        mesh=mesh,
        metrics=MetricsLogger(stream=open("/dev/null", "w")),
    ), model


class TestResNetTraining:
    @pytest.mark.slow  # ~20s ResNet compile; eval/batch-stats tests keep coverage
    def test_loss_decreases(self, trainer):
        tr, model = trainer
        state = tr.fit(fake_data(), num_steps=20, examples_per_step=BATCH,
                       log_every=0)
        assert int(state.step) == 20
        assert tr._last_metrics["loss"] < 1.2  # ln(4)=1.386 is chance level

    def test_batch_stats_updated(self, trainer):
        tr, model = trainer
        state = tr.create_state(seed=1)
        stats0 = jax.tree_util.tree_leaves(state.mutable)[0].copy()
        step = tr.compile_step()
        state, _ = step(state, tr.shard_batch(next(fake_data())))
        stats1 = jax.tree_util.tree_leaves(state.mutable)[0]
        assert not np.allclose(np.asarray(stats0), np.asarray(stats1))

    def test_state_sharded_over_batch_axis(self, trainer):
        tr, _ = trainer
        batch = tr.shard_batch(next(fake_data()))
        # 16-image batch over 8 devices: 2 images per shard.
        shard_shapes = {s.data.shape for s in batch["image"].addressable_shards}
        assert shard_shapes == {(2, IMG, IMG, 3)}

    def test_eval_step(self, trainer):
        tr, model = trainer
        state = tr.create_state(seed=2)
        metrics = eval_step(model)(state.params, state.mutable,
                                   next(fake_data()))
        assert 0.0 <= float(metrics["accuracy"]) <= 1.0


class TestMultiStepFusion:
    """fit(steps_per_call=k): k steps fused into one lax.scan program
    must follow the same trajectory as the per-step loop (same data
    order, same rng chain), on the sharded 8-device mesh."""

    def _run(self, trainer_model, steps_per_call):
        trainer, _ = trainer_model
        state = trainer.create_state(seed=7)
        state = trainer.fit(
            fake_data(3), 8, state=state, log_every=8,
            steps_per_call=steps_per_call,
        )
        return state, trainer.metrics.history[-1]["loss"]

    def test_fused_matches_per_step_trajectory(self, trainer):
        state1, loss1 = self._run(trainer, 1)
        state4, loss4 = self._run(trainer, 4)
        assert int(state1.step) == int(state4.step) == 8
        # Same data order, same rng chain; the residual difference is
        # compilation numerics (the scan program reassociates float ops
        # differently from the straight-line step), not semantics.
        np.testing.assert_allclose(loss1, loss4, rtol=1e-2)
        l1 = jax.tree_util.tree_leaves(state1.params)
        l4 = jax.tree_util.tree_leaves(state4.params)
        for a, b in zip(l1, l4):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=5e-3)

    @pytest.mark.slow  # ~22s; the fused-trajectory test keeps the identity signal
    def test_remainder_steps_run_per_step(self, trainer):
        """num_steps not divisible by k: the tail runs through the
        single-step program; total step count is exact."""
        trainer_obj, _ = trainer
        state = trainer_obj.create_state(seed=9)
        state = trainer_obj.fit(
            fake_data(4), 7, state=state, log_every=7, steps_per_call=3,
        )
        assert int(state.step) == 7

    @pytest.mark.slow  # ~22s; the fused-trajectory test keeps the identity signal
    def test_repeated_staged_batch_skips_stacking(self, trainer,
                                                  monkeypatch):
        """The repeat fast path must actually fire for a staged batch
        fed through an iterator: shard_batch rebuilds the dict but the
        LEAVES are identical, and that's what the dispatcher compares
        (review finding r3: container identity never matched)."""
        trainer_obj, _ = trainer
        state = trainer_obj.create_state(seed=11)
        b = trainer_obj.shard_batch(next(fake_data(6)))

        def rep(x):
            while True:
                yield x

        def boom(*a, **k):
            raise AssertionError(
                "stack_batches must not run for repeated staged batches")

        monkeypatch.setattr(trainer_obj, "stack_batches", boom)
        state = trainer_obj.fit(rep(b), 4, state=state, log_every=4,
                                steps_per_call=4)
        assert int(state.step) == 4

    def test_stack_batches_sharding(self, trainer):
        trainer_obj, _ = trainer
        batches = [trainer_obj.shard_batch(b)
                   for b, _ in zip(fake_data(5), range(3))]
        stacked = trainer_obj.stack_batches(batches)
        assert stacked["image"].shape == (3, BATCH, IMG, IMG, 3)
        # Batch dim (axis 1) stays sharded over the data axis.
        spec = stacked["image"].sharding.spec
        assert spec[0] is None and spec[1] is not None


class TestCheckpointResume:
    @pytest.mark.slow  # ~32s; train_resilience_smoke keeps the restore signal
    def test_restore_or_init_roundtrip(self, trainer, tmp_path):
        tr, _ = trainer
        with CheckpointManager(tmp_path / "ckpt", save_interval_steps=1) as mgr:
            tr_ck = Trainer(
                init_fn=tr.init_fn, loss_fn=tr.loss_fn, tx=tr.tx,
                mesh=tr.mesh, checkpoints=mgr, checkpoint_every=5,
                metrics=MetricsLogger(stream=open("/dev/null", "w")),
            )
            state = tr_ck.fit(fake_data(), num_steps=6,
                              examples_per_step=BATCH, log_every=0)
            assert mgr.latest_step() == 5

        # Simulate preemption: a fresh trainer+manager resumes at step 6.
        with CheckpointManager(tmp_path / "ckpt") as mgr2:
            tr2 = Trainer(
                init_fn=tr.init_fn, loss_fn=tr.loss_fn, tx=tr.tx,
                mesh=tr.mesh, checkpoints=mgr2,
                metrics=MetricsLogger(stream=open("/dev/null", "w")),
            )
            fresh = tr2.create_state()
            restored, start = mgr2.restore_or_init(fresh)
            assert start == 6
            np.testing.assert_allclose(
                np.asarray(jax.tree_util.tree_leaves(restored.params)[0]),
                np.asarray(jax.tree_util.tree_leaves(state.params)[0]),
            )


class TestResNetConfig:
    def test_build_all_depths(self):
        for name in ["resnet18", "resnet34", "resnet50"]:
            assert ResNetConfig(name=name).build() is not None

    def test_unknown_rejected(self):
        with pytest.raises(ValueError, match="unknown resnet"):
            ResNetConfig(name="resnet1b").build()

    @pytest.mark.slow  # ~25s resnet50 compile just for shapes
    def test_resnet50_shapes(self, devices):
        model = ResNetConfig(num_classes=10).build()
        vars_ = model.init(jax.random.key(0), jnp.zeros((1, 64, 64, 3)),
                           train=False)
        out = model.apply(vars_, jnp.zeros((2, 64, 64, 3)), train=False)
        assert out.shape == (2, 10)
        assert out.dtype == jnp.float32


class TestFsdpDataMesh:
    """The driver's 8-device layout must exercise dp AND fsdp > 1
    (VERDICT r1: grad averaging over `data` and ZeRO-3 sharding over
    `fsdp` are the production-critical axes)."""

    @pytest.fixture(scope="class")
    def lm_trainer(self, devices):
        from kubeflow_tpu.models.transformer import TransformerConfig, lm_task

        mesh = MeshSpec(data=2, fsdp=2, sequence=2).build(devices)
        cfg = TransformerConfig(
            vocab_size=128, d_model=32, n_layers=2, n_heads=2, n_kv_heads=2,
            d_ff=64, head_dim=16, max_seq_len=32, dtype=jnp.float32,
            attention="ring",
        )
        init_fn, loss_fn = lm_task(cfg, mesh=mesh)
        tr = Trainer(
            init_fn=init_fn, loss_fn=loss_fn, tx=optax.adamw(1e-3),
            mesh=mesh, metrics=MetricsLogger(stream=open("/dev/null", "w")),
        )
        return tr, cfg, mesh

    def test_params_fsdp_sharded(self, lm_trainer):
        tr, cfg, mesh = lm_trainer
        state = tr.create_state(seed=0)
        # Embed-dim (d_model) weight shards over fsdp per DEFAULT_RULES.
        wq = state.params["layers"]["attn"]["wq"]  # [layers, embed, heads, kv]
        spec = wq.sharding.spec
        assert "fsdp" in str(spec), spec
        shard = wq.addressable_shards[0].data
        assert shard.shape[1] == cfg.d_model // 2  # embed split across fsdp=2

    def test_optimizer_state_mirrors_param_sharding(self, lm_trainer):
        tr, _, _ = lm_trainer
        state = tr.create_state(seed=0)
        wq = state.params["layers"]["attn"]["wq"]
        mu = state.opt_state[0].mu["layers"]["attn"]["wq"]
        assert mu.sharding.spec == wq.sharding.spec

    def test_data_axis_grad_averaging(self, lm_trainer):
        """Identical per-shard batches -> grads equal the single-shard
        grads (psum-mean over data axis is exact averaging)."""
        tr, cfg, mesh = lm_trainer
        state = tr.create_state(seed=0)
        step = tr.compile_step()
        toks = np.tile(
            np.arange(32, dtype=np.int32)[None] % cfg.vocab_size, (8, 1)
        )
        batch = tr.shard_batch({"tokens": toks})
        state2, metrics = step(state, batch)
        assert np.isfinite(float(metrics["loss"]))
        assert float(metrics["grad_norm"]) > 0
        # Batch dim is sharded over (data, fsdp) = 4-way.
        arr = batch["tokens"]
        assert arr.addressable_shards[0].data.shape[0] == 2
