"""Unit tests for the typed param system.

Heir of kubeflow/core/tests/util_test.jsonnet:1-22 (toBool/toArray coercion
assertions) — same coverage, plus the error cases jsonnet silently passed.
"""

import pytest

from kubeflow_tpu.config import (
    Param,
    ParamError,
    Prototype,
    Registry,
    param,
    to_bool,
    to_list,
)


class TestCoercions:
    def test_to_bool_truthy(self):
        for v in (True, "true", "True", "TRUE", "yes", "1", 1, 2.5, "on"):
            assert to_bool(v) is True

    def test_to_bool_falsy(self):
        for v in (False, "false", "False", "no", "0", 0, 0.0, "", "off"):
            assert to_bool(v) is False

    def test_to_bool_garbage_raises(self):
        with pytest.raises(ParamError):
            to_bool("maybe")

    def test_to_list(self):
        assert to_list("a,b,c") == ["a", "b", "c"]
        assert to_list("a, b , c") == ["a", "b", "c"]
        assert to_list("") == []
        assert to_list(None) == []
        assert to_list(["x", 1]) == ["x", "1"]


class TestParam:
    def test_default(self):
        p = param("replicas", int, 3)
        assert p.coerce(None) == 3

    def test_string_to_int(self):
        assert param("replicas", int, 3).coerce("7") == 7

    def test_required_missing(self):
        with pytest.raises(ParamError, match="required"):
            param("name", str, required=True).coerce(None)

    def test_choices(self):
        p = param("cloud", str, "gke", choices=["gke", "minikube"])
        assert p.coerce("minikube") == "minikube"
        with pytest.raises(ParamError, match="not in"):
            p.coerce("aws")

    def test_bad_coercion(self):
        with pytest.raises(ParamError, match="coerce"):
            param("n", int).coerce("not-a-number")


def _echo_proto():
    return Prototype(
        name="echo",
        params=[param("namespace", str, "default"),
                param("replicas", int, 1)],
        generate=lambda name, namespace, replicas: [
            {"kind": "Echo", "metadata": {"name": name,
                                          "namespace": namespace},
             "spec": {"replicas": replicas}}],
    )


class TestPrototype:
    def test_generate_with_defaults(self):
        objs = _echo_proto().generate("mine")
        assert objs == [{"kind": "Echo",
                         "metadata": {"name": "mine", "namespace": "default"},
                         "spec": {"replicas": 1}}]

    def test_unknown_param_rejected(self):
        with pytest.raises(ParamError, match="unknown parameters"):
            _echo_proto().generate("mine", nope=1)

    def test_describe_lists_params(self):
        text = _echo_proto().describe()
        assert "--namespace" in text and "--replicas" in text


class TestRegistry:
    def test_register_and_generate(self):
        reg = Registry()
        reg.register(_echo_proto())
        assert reg.names() == ["echo"]
        objs = reg.generate("echo", "x", replicas="5")
        assert objs[0]["spec"]["replicas"] == 5

    def test_duplicate_rejected(self):
        reg = Registry()
        reg.register(_echo_proto())
        with pytest.raises(ParamError, match="already registered"):
            reg.register(_echo_proto())

    def test_unknown_prototype(self):
        with pytest.raises(ParamError, match="unknown prototype"):
            Registry().get("nope")


class TestApp:
    def test_render_flow(self):
        from kubeflow_tpu.config.registry import App

        reg = Registry()
        reg.register(_echo_proto())
        app = App(namespace="kubeflow", registry=reg)
        app.add("echo", "one").add("echo", "two", replicas=2)
        app.set_param("two", "replicas", 9)
        objs = app.render()
        assert [o["metadata"]["name"] for o in objs] == ["one", "two"]
        # App namespace flows into components that declare a namespace param.
        assert objs[0]["metadata"]["namespace"] == "kubeflow"
        assert objs[1]["spec"]["replicas"] == 9

    def test_add_validates_eagerly(self):
        from kubeflow_tpu.config.registry import App

        reg = Registry()
        reg.register(_echo_proto())
        with pytest.raises(ParamError):
            App(registry=reg).add("echo", "x", bogus=True)
