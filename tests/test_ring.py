"""Ring attention vs single-device reference on the fake slice."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_tpu.ops.attention import dot_product_attention
from kubeflow_tpu.parallel import MeshSpec
from kubeflow_tpu.parallel.ring import make_ring_attention


def rand_qkv(rng, b=2, s=32, h=2, d=16):
    q = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    return q, k, v


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("seq_parallel", [2, 4, 8])
def test_matches_reference(devices, causal, seq_parallel):
    mesh = MeshSpec(data=1, sequence=seq_parallel).build(devices[:seq_parallel])
    rng = np.random.RandomState(0)
    q, k, v = rand_qkv(rng, s=32)
    ref = dot_product_attention(q, k, v, causal=causal)
    ring = make_ring_attention(mesh, causal=causal)
    out = jax.jit(ring)(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_mixed_mesh_dp_sp_tp(devices):
    """batch, sequence, and heads all sharded at once."""
    mesh = MeshSpec(data=2, sequence=2, tensor=2).build(devices)
    rng = np.random.RandomState(1)
    q, k, v = rand_qkv(rng, b=4, s=16, h=4, d=8)
    ref = dot_product_attention(q, k, v, causal=True)
    out = jax.jit(make_ring_attention(mesh))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_gradients_match(devices):
    mesh = MeshSpec(data=1, sequence=4).build(devices[:4])
    rng = np.random.RandomState(2)
    q, k, v = rand_qkv(rng, b=1, s=16, h=1, d=8)
    ring = make_ring_attention(mesh, causal=True)

    g_ring = jax.grad(lambda *a: jax.jit(ring)(*a).sum(), argnums=(0, 1, 2))(
        q, k, v)
    g_ref = jax.grad(
        lambda *a: dot_product_attention(*a, causal=True).sum(),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-4)


@pytest.mark.parametrize("causal", [True, False])
def test_gqa_ring(devices, causal):
    """kv heads rotate unrepeated; broadcast happens inside each hop."""
    mesh = MeshSpec(data=1, sequence=4).build(devices[:4])
    rng = np.random.RandomState(3)
    b, s, h, hkv, d = 1, 32, 4, 2, 16
    q = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, s, hkv, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, s, hkv, d), jnp.float32)
    ref = dot_product_attention(q, k, v, causal=causal)
    out = jax.jit(make_ring_attention(mesh, causal=causal))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    g_ring = jax.grad(
        lambda *a: jax.jit(make_ring_attention(mesh, causal=causal))(*a).sum(),
        argnums=(0, 1, 2),
    )(q, k, v)
    g_ref = jax.grad(
        lambda *a: dot_product_attention(*a, causal=causal).sum(),
        argnums=(0, 1, 2),
    )(q, k, v)
    assert g_ring[1].shape == k.shape
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-4)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_with_flash_blocks(devices, causal):
    """Ring x flash composition: each hop runs the Pallas kernel
    (interpreter) instead of the XLA block; fwd AND bwd must match."""
    mesh = MeshSpec(data=1, sequence=2).build(devices[:2])
    rng = np.random.RandomState(4)
    q, k, v = rand_qkv(rng, b=1, s=32, h=2, d=16)
    ring = make_ring_attention(mesh, causal=causal, block_q=8, block_k=8)

    from kubeflow_tpu.parallel import ring as ring_mod
    from kubeflow_tpu.parallel.ring import ring_attention
    import functools as ft
    from jax.sharding import PartitionSpec as P

    spec = P(None, "sequence", None, None)

    # check_vma=False: the Pallas *interpreter* can't discharge
    # dynamic_slice with varying manual axes (real-TPU lowering can; the
    # production path keeps vma checking on).
    @ft.partial(jax.shard_map, mesh=mesh, in_specs=(spec, spec, spec),
                out_specs=spec, check_vma=False)
    def flash_ring(q, k, v):
        return ring_attention(q, k, v, causal=causal, block_q=8, block_k=8,
                              interpret=True)

    ref = dot_product_attention(q, k, v, causal=causal)
    out = jax.jit(flash_ring)(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    g_ring = jax.grad(lambda *a: jax.jit(flash_ring)(*a).sum(),
                      argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(lambda *a: dot_product_attention(*a, causal=causal).sum(),
                     argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4)
