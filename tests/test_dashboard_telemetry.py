"""Dashboard (central + TPUJob browser) and usage-telemetry tests —
the first-party heirs of centraldashboard.libsonnet, the tf-job
dashboard (tf-job-operator.libsonnet:417-450), and spartakus
(spartakus.libsonnet:4-14)."""

import json
import threading
import urllib.request
from http.server import BaseHTTPRequestHandler, HTTPServer

import numpy as np  # noqa: F401 — keeps conftest platform setup uniform

from kubeflow_tpu.operator import crd
from kubeflow_tpu.operator.kube import FakeKube
from kubeflow_tpu.tools.dashboard import (
    DashboardAPI,
    job_rows,
    make_server,
    render_central,
)
from kubeflow_tpu.tools.telemetry import collect, report


def _fake_kube_with_job():
    kube = FakeKube()
    cr = crd.TPUJobSpec(name="mnist", namespace="kubeflow",
                        slice_type="v5e-8",
                        num_slices=2).to_custom_resource()
    cr["status"] = {"phase": "Running", "restarts": 1}
    kube.create_custom(cr)
    return kube


class TestCentralDashboard:
    def test_landing_page_links(self):
        page = render_central()
        assert "/hub/" in page and "/tpujobs/" in page

    def test_http_roundtrip(self):
        httpd, _ = make_server("central", 0, host="127.0.0.1")
        port = httpd.server_address[1]
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/", timeout=10) as resp:
                assert "Kubeflow-TPU" in resp.read().decode()
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/healthz", timeout=10) as resp:
                assert json.loads(resp.read())["status"] == "ok"
        finally:
            httpd.shutdown()


class TestTPUJobDashboard:
    def test_job_rows_from_crs(self):
        rows = job_rows(_fake_kube_with_job())
        assert rows == [{
            "name": "mnist", "namespace": "kubeflow", "phase": "Running",
            "slice_type": "v5e-8", "num_slices": 2, "restarts": 1,
        }]

    def test_html_and_json_routes(self):
        httpd, _ = make_server("tpujobs", 0, host="127.0.0.1",
                               kube=_fake_kube_with_job())
        port = httpd.server_address[1]
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/tpujobs/", timeout=10) as r:
                html = r.read().decode()
            assert "mnist" in html and "Running" in html
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/tpujobs/api/jobs",
                    timeout=10) as r:
                jobs = json.loads(r.read())["jobs"]
            assert jobs[0]["slice_type"] == "v5e-8"
        finally:
            httpd.shutdown()

    def test_empty_cluster_renders(self):
        api = DashboardAPI("tpujobs", kube=FakeKube())
        page, ctype = api.tpujobs_html()
        assert "No TPUJobs" in page and ctype == "text/html"


class TestTelemetry:
    def test_collect_payload_is_anonymous(self):
        kube = _fake_kube_with_job()
        kube.nodes.append({"metadata": {"name": "node-a"}})
        payload = collect("uid-123", kube=kube)
        assert payload["usage_id"] == "uid-123"
        assert payload["framework_version"]
        assert payload["node_count"] == 1
        # No identifying fields beyond the opaque usage id.
        assert set(payload) <= {"usage_id", "framework_version",
                                "jax_version", "node_count"}

    def test_report_log_only(self):
        assert report({"usage_id": "x"}, url=None) is True

    def test_report_posts_json(self):
        received = {}

        class Collector(BaseHTTPRequestHandler):
            def do_POST(self):
                n = int(self.headers["Content-Length"])
                received.update(json.loads(self.rfile.read(n)))
                self.send_response(204)
                self.end_headers()

            def log_message(self, *a):
                pass

        httpd = HTTPServer(("127.0.0.1", 0), Collector)
        threading.Thread(target=httpd.handle_request, daemon=True).start()
        port = httpd.server_address[1]
        ok = report({"usage_id": "y"},
                    url=f"http://127.0.0.1:{port}/report")
        httpd.server_close()
        assert ok and received == {"usage_id": "y"}