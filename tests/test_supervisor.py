"""Training supervisor: restart-with-backoff, heartbeat, stall
watchdog (runtime/supervisor.py) + Trainer.fit's supervision hooks.

Fake trainers drive the policy paths (budget, stall, clock skew) in
microseconds; one real tiny SPMD trainer proves the loss-identity
contract — a supervised run with a mid-run fault ends bit-identical to
an uninterrupted run of the same seed.
"""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from kubeflow_tpu.data.loader import DataError
from kubeflow_tpu.parallel import MeshSpec
from kubeflow_tpu.runtime.checkpoint import CheckpointError, CheckpointManager
from kubeflow_tpu.runtime.metrics import MetricsLogger
from kubeflow_tpu.runtime.prom import REGISTRY, parse_metrics, sample_value
from kubeflow_tpu.runtime.supervisor import (
    RESTARTABLE,
    RestartBudgetExceeded,
    StallDetected,
    TrainSupervisor,
)
from kubeflow_tpu.runtime.train import Trainer
from kubeflow_tpu.testing import faults


def counter(name, **labels):
    return sample_value(parse_metrics(REGISTRY.render()),
                        name, **labels) or 0.0


class FakeTrainer:
    """fit() that walks the step counter and obeys injected faults —
    the supervisor only sees the Trainer.fit contract (on_step +
    exceptions), so this is a faithful stand-in for policy tests."""

    def __init__(self, resume_at=0, raise_once=None):
        self.calls = 0
        self.resume_at = resume_at  # "restored checkpoint" step
        self.raise_once = raise_once

    def fit(self, data, num_steps, on_step=None, **kw):
        self.calls += 1
        start = 0 if self.calls == 1 else self.resume_at
        for i in range(start, num_steps):
            faults.fire("train.step")
            if self.raise_once is not None:
                exc, self.raise_once = self.raise_once, None
                raise exc
            if on_step is not None:
                on_step(i + 1)
        return "final-state"


class TestRestartPolicy:
    def test_step_fault_restarts_and_counts(self):
        before = counter("kft_train_restarts_total", reason="step")
        with faults.injected("train.step:raise*1;train.step:skew=60"):
            tr = FakeTrainer(resume_at=2)
            sup = TrainSupervisor(tr, max_restarts=2, backoff_s=5.0)
            out = sup.run(lambda: None, 5)
        assert out == "final-state"
        assert sup.restarts == 1 and tr.calls == 2
        assert counter("kft_train_restarts_total",
                       reason="step") == before + 1

    def test_budget_exceeded_raises_with_cause(self):
        with faults.injected("train.step:raise;train.step:skew=60"):
            sup = TrainSupervisor(FakeTrainer(), max_restarts=1,
                                  backoff_s=1.0)
            with pytest.raises(RestartBudgetExceeded) as exc:
                sup.run(lambda: None, 3)
        assert isinstance(exc.value.__cause__, faults.FaultInjected)
        assert sup.restarts == 2  # the budget-breaking attempt counted

    def test_zero_budget_means_fail_fast(self):
        with faults.injected("train.step:raise*1"):
            sup = TrainSupervisor(FakeTrainer(), max_restarts=0)
            with pytest.raises(RestartBudgetExceeded):
                sup.run(lambda: None, 3)

    def test_data_error_is_restartable(self):
        with faults.injected("seed=0"):
            tr = FakeTrainer(resume_at=1,
                             raise_once=DataError("retry budget spent"))
            sup = TrainSupervisor(tr, max_restarts=1, backoff_s=0.0)
            assert sup.run(lambda: None, 3) == "final-state"
        assert sup.restarts == 1

    def test_checkpoint_error_is_restartable(self):
        with faults.injected("seed=0"):
            tr = FakeTrainer(resume_at=1,
                             raise_once=CheckpointError("async died"))
            sup = TrainSupervisor(tr, max_restarts=1, backoff_s=0.0)
            assert sup.run(lambda: None, 3) == "final-state"
        assert sup.restarts == 1

    def test_non_restartable_propagates_unwrapped(self):
        tr = FakeTrainer(raise_once=ValueError("a real bug"))
        sup = TrainSupervisor(tr, max_restarts=3, backoff_s=0.0)
        with pytest.raises(ValueError):
            sup.run(lambda: None, 3)
        assert sup.restarts == 0

    def test_fresh_data_iterable_per_attempt(self):
        factories = []
        with faults.injected("train.step:raise*1;train.step:skew=60"):
            sup = TrainSupervisor(FakeTrainer(resume_at=1),
                                  max_restarts=1, backoff_s=1.0)
            sup.run(lambda: factories.append(1) or iter(()), 3)
        assert len(factories) == 2  # one fresh iterable per attempt

    def test_restartable_set_is_typed(self):
        assert faults.FaultInjected in RESTARTABLE
        assert DataError in RESTARTABLE
        assert CheckpointError in RESTARTABLE
        assert StallDetected in RESTARTABLE
        assert ValueError not in RESTARTABLE


class TestBackoff:
    def test_backoff_waits_on_the_policy_clock(self):
        """A 100s backoff must expire from clock skew alone — no wall
        sleeping (the clock-discipline contract)."""
        with faults.injected("seed=0") as inj:
            sup = TrainSupervisor(FakeTrainer(), backoff_s=100.0,
                                  backoff_max_s=100.0)
            done = threading.Event()

            def waiter():
                sup._backoff(1)
                done.set()

            t = threading.Thread(target=waiter, daemon=True)
            t.start()
            assert not done.wait(0.2), "backoff returned early"
            inj.advance_clock(1000)
            assert done.wait(5.0), "skewed clock did not expire backoff"
            t.join()

    def test_backoff_is_capped(self):
        with faults.injected("seed=0") as inj:
            sup = TrainSupervisor(FakeTrainer(), backoff_s=1.0,
                                  backoff_max_s=2.0)
            inj.advance_clock(0)  # injector installed for the clock
            t0 = time.perf_counter()
            done = threading.Event()
            t = threading.Thread(
                target=lambda: (sup._backoff(10), done.set()),
                daemon=True)
            t.start()
            inj.advance_clock(3.0)  # > cap x max jitter
            assert done.wait(5.0)
            t.join()
            assert time.perf_counter() - t0 < 5.0


class TestStallWatchdog:
    def test_skewed_clock_flags_stall_and_restarts(self):
        """The acceptance scenario: a dispatch that takes 500 policy-
        seconds against a millisecond rolling window is a stall; the
        next call boundary raises and the supervisor restarts."""

        class StallingTrainer(FakeTrainer):
            def fit(self, data, num_steps, on_step=None, **kw):
                self.calls += 1
                inj = faults.active()
                start = 0 if self.calls == 1 else self.resume_at
                for i in range(start, num_steps):
                    if self.calls == 1 and i == 6:
                        inj.advance_clock(500)  # the wedged dispatch
                    if on_step is not None:
                        on_step(i + 1)
                return "final-state"

        before = counter("kft_train_restarts_total", reason="stall")
        with faults.injected("seed=0") as inj:
            tr = StallingTrainer(resume_at=6)
            sup = TrainSupervisor(tr, max_restarts=1, backoff_s=50.0,
                                  min_stall_s=0.5, stall_factor=5.0,
                                  min_window=3)
            skewer = threading.Timer(0.2,
                                     lambda: inj.advance_clock(1000))
            skewer.start()  # expires the restart backoff, not walls
            try:
                assert sup.run(lambda: None, 8) == "final-state"
            finally:
                skewer.cancel()
        assert sup.restarts == 1
        assert counter("kft_train_restarts_total",
                       reason="stall") == before + 1

    def test_watchdog_pins_gauge_during_wedged_dispatch(self):
        """A dispatch that never returns cannot be restarted in
        process — but the watchdog thread must pin kft_train_stalled
        at 1 so external liveness machinery sees it."""
        release = threading.Event()
        stalled_seen = threading.Event()

        class WedgedTrainer:
            calls = 0

            def fit(self, data, num_steps, on_step=None, **kw):
                self.calls += 1
                if self.calls > 1:  # post-restart attempt: healthy
                    for i in range(3, num_steps):
                        on_step(i + 1)
                    return "final-state"
                for i in range(3):  # establish the rolling window
                    on_step(i + 1)
                faults.active().advance_clock(500)
                release.wait(10.0)
                on_step(4)  # boundary AFTER the stall -> StallDetected
                return "unreachable"

        with faults.injected("seed=0"):
            sup = TrainSupervisor(WedgedTrainer(), max_restarts=1,
                                  backoff_s=0.0, min_stall_s=0.5,
                                  stall_factor=5.0, min_window=2,
                                  heartbeat_s=0.02)

            def watch_gauge():
                deadline = time.perf_counter() + 5.0
                while time.perf_counter() < deadline:
                    g = sample_value(
                        parse_metrics(REGISTRY.render()),
                        "kft_train_stalled")
                    if g == 1.0:
                        stalled_seen.set()
                        release.set()
                        return
                    time.sleep(0.01)
                release.set()

            t = threading.Thread(target=watch_gauge, daemon=True)
            t.start()
            out = sup.run(lambda: None, 6)
            t.join()
        assert stalled_seen.is_set(), (
            "watchdog never exported kft_train_stalled=1")
        assert out == "final-state" and sup.restarts == 1

    def test_no_stall_verdict_before_min_window(self):
        with faults.injected("seed=0") as inj:
            calls = {"n": 0}

            class SlowFirstSteps(FakeTrainer):
                def fit(self, data, num_steps, on_step=None, **kw):
                    calls["n"] += 1
                    for i in range(num_steps):
                        inj.advance_clock(100)  # every "step" is slow
                        on_step(i + 1)
                    return "final-state"

            sup = TrainSupervisor(SlowFirstSteps(), max_restarts=0,
                                  min_window=100)
            # Window never fills -> no threshold -> no stall raise.
            assert sup.run(lambda: None, 5) == "final-state"

    def test_heartbeat_age_reads_policy_clock(self):
        with faults.injected("seed=0") as inj:
            sup = TrainSupervisor(FakeTrainer(), max_restarts=0)
            sup.run(lambda: None, 3)
            inj.advance_clock(50)
            assert sup.stats()["heartbeat_age_s"] >= 50

    def test_user_on_step_chains(self):
        seen = []
        sup = TrainSupervisor(FakeTrainer(), max_restarts=0)
        sup.run(lambda: None, 4, on_step=seen.append)
        assert seen == [1, 2, 3, 4]
        assert sup.steps_seen == seen


def tiny_task():
    def init_fn(rng):
        return {"w": jax.random.normal(rng, (4,))}, {}

    def loss_fn(params, mutable, batch, rng):
        pred = batch["x"] @ params["w"]
        loss = jnp.mean((pred - batch["y"]) ** 2)
        return loss, ({}, mutable)

    return init_fn, loss_fn


def tiny_data():
    rng = np.random.RandomState(0)
    while True:
        x = rng.randn(16, 4).astype(np.float32)
        yield {"x": x, "y": (x @ np.array([1, -1, 2, 0.5],
                                          np.float32))}


class TestSupervisedTrainerIdentity:
    """The real thing: Trainer.fit under the supervisor, fault-injected
    mid-run, must finish with params identical to an uninterrupted run
    of the same seed — resume replays from the verified checkpoint and
    the data stream re-aligns."""

    def make_trainer(self, devices, ckpt_dir):
        init_fn, loss_fn = tiny_task()
        return Trainer(
            init_fn=init_fn, loss_fn=loss_fn, tx=optax.sgd(0.1),
            mesh=MeshSpec(data=8).build(devices),
            checkpoints=CheckpointManager(ckpt_dir, max_to_keep=3),
            checkpoint_every=2,
            metrics=MetricsLogger(stream=open("/dev/null", "w")))

    def test_fault_mid_run_params_identical(self, devices, tmp_path):
        control = self.make_trainer(devices, tmp_path / "control")
        control_state = TrainSupervisor(control, max_restarts=0).run(
            tiny_data, 6, log_every=0)
        control.checkpoints.close()

        trainer = self.make_trainer(devices, tmp_path / "victim")
        sup = TrainSupervisor(trainer, max_restarts=2, backoff_s=5.0)
        # Warm 4 steps (checkpoints land at 1 and 3), then fault the
        # continuation's first step; skew expires the backoff.
        sup.run(tiny_data, 4, log_every=0)
        assert trainer.checkpoints.latest_verified_step() == 3
        with faults.injected("train.step:raise*1;train.step:skew=60"):
            final = sup.run(tiny_data, 6, log_every=0)
        trainer.checkpoints.close()
        assert sup.restarts == 1
        boundaries = sup.steps_seen
        assert boundaries == sorted(boundaries)  # monotone, never 0
        assert boundaries[-1] == 6
        np.testing.assert_array_equal(
            np.asarray(final.params["w"]),
            np.asarray(control_state.params["w"]))
        assert int(final.step) == int(control_state.step) == 6

    def test_train_step_hook_fires_per_loop_iteration(self, devices,
                                                      tmp_path):
        trainer = self.make_trainer(devices, tmp_path / "hook")
        with faults.injected("seed=0") as inj:
            trainer.fit(tiny_data(), 3, log_every=0)
            assert inj.fired("train.step") == 3
        trainer.checkpoints.close()


class TestReviewRegressions:
    def test_backoff_window_does_not_read_stale_heartbeat(self):
        """The failed attempt's heartbeat/window are cleared BEFORE
        the backoff wait — the watchdog must not pin
        kft_train_stalled=1 against a stale beat during a healthy
        supervised restart."""
        observed = {}
        with faults.injected("train.step:raise*1;train.step:skew=60"):
            sup = TrainSupervisor(FakeTrainer(resume_at=1),
                                  max_restarts=1, backoff_s=5.0)
            orig = sup._backoff

            def spy(attempt):
                observed["beat"] = sup.stats()["heartbeat_age_s"]
                observed["stalled_gauge"] = sample_value(
                    parse_metrics(REGISTRY.render()),
                    "kft_train_stalled")
                orig(attempt)

            sup._backoff = spy
            sup.run(lambda: None, 4)
        assert observed["beat"] is None, (
            "stale heartbeat survived into the backoff window")
        assert observed["stalled_gauge"] == 0.0
