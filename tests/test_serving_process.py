"""Deployed-entrypoint test: spawn serving/main.py as a real process and
exercise BOTH wire protocols against it — the gRPC PredictionService
(the reference's primary protocol, tensorflow_model_server :9000,
kubeflow/tf-serving/tf-serving.libsonnet:118-132) and the REST contract
(:176-207) — proving the container entrypoint the manifests deploy
actually serves what the manifests expose."""

import json
import os
import pathlib
import re
import subprocess
import sys
import urllib.request

import jax
import numpy as np
import pytest

from kubeflow_tpu.models.resnet import ResNet18
from kubeflow_tpu.serving.export import export

CLASSES, IMG = 4, 32


@pytest.fixture(scope="module")
def served_process(tmp_path_factory):
    base = tmp_path_factory.mktemp("proc_models") / "tiny"
    model = ResNet18(num_classes=CLASSES, num_filters=8)
    variables = model.init(
        jax.random.key(0), np.zeros((1, IMG, IMG, 3), np.float32),
        train=False,
    )
    export(
        base, 1, variables,
        loader="kubeflow_tpu.serving.loaders:classifier",
        config={"family": "resnet18", "num_classes": CLASSES, "top_k": 2,
                "num_filters": 8},
        signature={"inputs": ["image"],
                   "outputs": ["scores", "top_k_scores", "top_k_classes"]},
    )
    # PYTHONPATH pinned to the repo: the spawned CPU-only server must
    # not inherit environment-injected jax plugin paths (a dead device
    # tunnel would hang its jax init; `python -m` plus this keeps the
    # package importable and the process hermetic).
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=str(pathlib.Path(__file__).parents[1]))
    proc = subprocess.Popen(
        [sys.executable, "-m", "kubeflow_tpu.serving.main",
         "--model_name", "tiny", "--model_base_path", str(base),
         "--port", "0", "--grpc_port", "0"],
        stderr=subprocess.PIPE, text=True, env=env,
    )
    # Readiness scan runs on a helper thread so a silently-hung server
    # cannot block the suite forever: the main thread waits on an event
    # with a hard deadline and kills the process on timeout.
    import threading

    found = {}
    ready = threading.Event()

    def scan():
        for line in proc.stderr:
            m = re.search(r"KFT_SERVING_READY rest=(\d+) grpc=(\d+)", line)
            if m:
                found["ports"] = int(m.group(1)), int(m.group(2))
                ready.set()
                return
        ready.set()  # EOF without the marker — process died

    threading.Thread(target=scan, daemon=True).start()
    if not ready.wait(timeout=180) or "ports" not in found:
        proc.kill()
        pytest.fail("serving process never became ready")
    ports = found["ports"]
    yield proc, ports
    proc.terminate()
    proc.wait(timeout=10)


class TestServingProcess:
    def test_rest_predict_and_health(self, served_process):
        _, (rest_port, _) = served_process
        rng = np.random.RandomState(0)
        body = json.dumps({
            "instances": [
                {"image": rng.randn(IMG, IMG, 3).astype(np.float32).tolist()}
            ]
        }).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{rest_port}/model/tiny:predict",
            data=body, headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=60) as resp:
            out = json.loads(resp.read())
        assert len(out["predictions"]) == 1
        assert len(out["predictions"][0]["scores"]) == CLASSES

        with urllib.request.urlopen(
            f"http://127.0.0.1:{rest_port}/healthz", timeout=60
        ) as resp:
            health = json.loads(resp.read())
        assert health["models"] == {"tiny": [1]}

    def test_grpc_predict_and_metadata(self, served_process):
        from kubeflow_tpu.serving.grpc_server import PredictionClient

        _, (_, grpc_port) = served_process
        client = PredictionClient(f"127.0.0.1:{grpc_port}")
        rng = np.random.RandomState(1)
        img = rng.randn(2, IMG, IMG, 3).astype(np.float32)
        out = client.predict("tiny", {"image": img}, timeout=120.0)
        assert out["scores"].shape == (2, CLASSES)
        np.testing.assert_allclose(out["scores"].sum(-1), 1.0, atol=1e-3)
        meta = client.metadata("tiny", timeout=60.0)
        assert meta["version"] == 1
        client.close()

    def test_manifest_deploys_both_protocols(self):
        """The deployed container/Service expose exactly the ports the
        entrypoint binds (the round-2 gap: gRPC tested in-process but
        absent from the deployment)."""
        import kubeflow_tpu.manifests  # noqa: F401 — registers prototypes
        from kubeflow_tpu.config.registry import default_registry

        deploy, svc = default_registry.generate(
            "tpu-serving", "m", model_name="m")[:2]
        container = deploy["spec"]["template"]["spec"]["containers"][0]
        assert "--grpc_port=9000" in container["args"]
        assert {p["containerPort"] for p in container["ports"]} == \
            {8000, 9000}
        assert {p["port"] for p in svc["spec"]["ports"]} == {8000, 9000}
