"""Operator tests: gang admission, pod materialization, env contract,
failure -> gang restart from checkpoint, preemption, queue FIFO.

The reference could only test its operator E2E on rented clusters
(SURVEY.md §4); the FakeKube makes the full lifecycle hermetic.
"""

import pytest

from kubeflow_tpu.operator import crd
from kubeflow_tpu.operator.gang import GangScheduler
from kubeflow_tpu.operator.kube import FAILED, RUNNING, SUCCEEDED, FakeKube
from kubeflow_tpu.operator.reconciler import (
    JOB_FAILED,
    JOB_RUNNING,
    JOB_SUCCEEDED,
    QUEUED,
    STARTING,
    TPUJobController,
    coordinator_address,
    worker_name,
)
from kubeflow_tpu.runtime import bootstrap


def make_cr(name="train", slice_type="v5e-16", **spec_overrides):
    job = crd.TPUJobSpec(name=name, slice_type=slice_type, **spec_overrides)
    return job.to_custom_resource()


@pytest.fixture()
def cluster():
    kube = FakeKube()
    sched = GangScheduler({"v5e-16": 2, "v5p-32": 1})
    return kube, sched, TPUJobController(kube, sched)


def set_all_pods(kube, ns, phase):
    for pod in kube.list_pods(ns):
        kube.set_pod_phase(ns, pod["metadata"]["name"], phase)


class TestHappyPath:
    def test_full_lifecycle(self, cluster):
        kube, sched, ctl = cluster
        kube.create_custom(make_cr())
        cr = kube.list_custom()[0]

        # First pass: admitted, pods created. v5e-16 has 4 hosts.
        assert ctl.reconcile_once(cr) == STARTING
        pods = kube.list_pods("kubeflow")
        assert len(pods) == 4
        assert ("kubeflow", "train") in kube.services

        # Kubelet "starts" the pods.
        set_all_pods(kube, "kubeflow", RUNNING)
        assert ctl.reconcile_once(cr) == JOB_RUNNING
        assert any(m["event"] == "gang_running" for m in ctl.metrics)

        set_all_pods(kube, "kubeflow", SUCCEEDED)
        assert ctl.reconcile_once(cr) == JOB_SUCCEEDED
        # Slices released for the next job.
        assert sched.free("v5e-16") == 2

    def test_env_contract(self, cluster):
        kube, _, ctl = cluster
        kube.create_custom(make_cr())
        ctl.reconcile_once(kube.list_custom()[0])
        pod = kube.get_pod("kubeflow", "train-worker-2")
        env = {e["name"]: e["value"]
               for e in pod["spec"]["containers"][0]["env"]}
        assert env[bootstrap.ENV_PROCESS_ID] == "2"
        assert env[bootstrap.ENV_NUM_PROCESSES] == "4"
        assert env[bootstrap.ENV_COORDINATOR] == \
            "train-worker-0.train.kubeflow:8476"
        # The bootstrap module can consume exactly this env.
        wenv = bootstrap.worker_env(env)
        assert wenv.process_id == 2 and wenv.num_processes == 4

    def test_pod_shape(self, cluster):
        kube, _, ctl = cluster
        kube.create_custom(make_cr())
        ctl.reconcile_once(kube.list_custom()[0])
        pod = kube.get_pod("kubeflow", "train-worker-0")
        container = pod["spec"]["containers"][0]
        # v5e-16: 16 chips / 4 hosts = 4 chips per pod; no nvidia.com/gpu.
        assert container["resources"]["limits"] == {"google.com/tpu": "4"}
        assert pod["spec"]["restartPolicy"] == "Never"
        assert pod["spec"]["subdomain"] == "train"
        sel = pod["spec"]["nodeSelector"]
        assert sel["cloud.google.com/gke-tpu-accelerator"] == \
            "tpu-v5-lite-podslice"

    def test_cpu_gang_pod_shape(self):
        """cpu-N slices (TPU-less E2E clusters, ci/run_e2e_kind.sh):
        pods schedule anywhere, no TPU resource or selector — the
        reference's minikube CPU-TFJob shape
        (tf-controller-examples/tf-cnn/create_job_specs.py:111)."""
        from kubeflow_tpu.operator import crd
        from kubeflow_tpu.operator.gang import GangScheduler
        from kubeflow_tpu.operator.kube import FakeKube
        from kubeflow_tpu.operator.reconciler import TPUJobController

        kube = FakeKube()
        ctl = TPUJobController(kube, GangScheduler({"cpu-2": 1}))
        job = crd.TPUJobSpec(name="cpujob", namespace="kubeflow",
                             slice_type="cpu-2")
        kube.create_custom(job.to_custom_resource())
        ctl.reconcile_once(kube.list_custom()[0])
        pods = kube.list_pods("kubeflow")
        assert len(pods) == 2  # one per host
        container = pods[0]["spec"]["containers"][0]
        assert "google.com/tpu" not in str(container["resources"])
        assert pods[0]["spec"]["nodeSelector"] == {}


class TestGangSemantics:
    def test_all_or_nothing_admission(self, cluster):
        kube, sched, ctl = cluster
        kube.create_custom(make_cr("a", slice_type="v5p-32"))
        kube.create_custom(make_cr("b", slice_type="v5p-32"))
        a, b = kube.list_custom()
        assert ctl.reconcile_once(a) == STARTING
        # Only one v5p-32 slice exists: b queues, creates NO pods.
        assert ctl.reconcile_once(b) == QUEUED
        assert all(p["metadata"]["name"].startswith("a-")
                   for p in kube.list_pods("kubeflow"))

        # a completes -> b admitted on next pass.
        set_all_pods(kube, "kubeflow", SUCCEEDED)
        assert ctl.reconcile_once(a) == JOB_SUCCEEDED
        assert ctl.reconcile_once(b) == STARTING

    def test_worker_failure_restarts_whole_gang(self, cluster):
        kube, _, ctl = cluster
        kube.create_custom(make_cr())
        cr = kube.list_custom()[0]
        ctl.reconcile_once(cr)
        set_all_pods(kube, "kubeflow", RUNNING)
        ctl.reconcile_once(cr)

        kube.set_pod_phase("kubeflow", "train-worker-1", FAILED)
        assert ctl.reconcile_once(cr) == STARTING
        assert cr["status"]["restarts"] == 1
        # ALL pods were torn down, not just the failed one.
        assert len(kube.deleted_pods) == 4
        # Next pass recreates the full gang.
        ctl.reconcile_once(cr)
        assert len(kube.list_pods("kubeflow")) == 4

    def test_preempted_pod_is_gang_failure(self, cluster):
        kube, _, ctl = cluster
        kube.create_custom(make_cr())
        cr = kube.list_custom()[0]
        ctl.reconcile_once(cr)
        set_all_pods(kube, "kubeflow", RUNNING)
        ctl.reconcile_once(cr)

        # Preemption: pod object disappears entirely.
        kube.delete_pod("kubeflow", "train-worker-3")
        assert ctl.reconcile_once(cr) == STARTING
        assert cr["status"]["restarts"] == 1

    def test_max_restarts_fails_job(self, cluster):
        kube, sched, ctl = cluster
        kube.create_custom(make_cr(
            restart=crd.RestartPolicy(max_restarts=1)))
        cr = kube.list_custom()[0]
        for expected_restarts in (1,):
            ctl.reconcile_once(cr)
            set_all_pods(kube, "kubeflow", RUNNING)
            ctl.reconcile_once(cr)
            kube.set_pod_phase("kubeflow", "train-worker-0", FAILED)
            assert ctl.reconcile_once(cr) == STARTING
            assert cr["status"]["restarts"] == expected_restarts
        ctl.reconcile_once(cr)
        set_all_pods(kube, "kubeflow", RUNNING)
        ctl.reconcile_once(cr)
        kube.set_pod_phase("kubeflow", "train-worker-0", FAILED)
        assert ctl.reconcile_once(cr) == JOB_FAILED
        assert sched.free("v5e-16") == 2  # slices released

    def test_invalid_spec_fails_cleanly(self, cluster):
        kube, _, ctl = cluster
        cr = make_cr()
        cr["spec"]["sliceType"] = "v99-1024"
        kube.create_custom(cr)
        ctl.reconcile_all()
        status = kube.get_custom("kubeflow", "train")["status"]
        assert status["phase"] == JOB_FAILED
        assert status["reason"] == "InvalidSpec"


class TestSchedulerQueue:
    def test_fifo_no_starvation(self):
        sched = GangScheduler({"v5e-16": 2})
        assert sched.offer("big", "v5e-16", 2)
        # head-of-line: small fits capacity-wise but big2 is ahead.
        assert not sched.offer("big2", "v5e-16", 2)
        assert not sched.offer("small", "v5e-16", 1)
        sched.release("big")
        assert sched.admitted("big2")
        assert not sched.admitted("small")

    def test_unsatisfiable_flagged(self):
        sched = GangScheduler({"v5e-16": 1})
        assert not sched.offer("huge", "v5e-16", 5)
        assert sched.queue[0].get("unsatisfiable")

    def test_metrics_recorded(self):
        sched = GangScheduler({"v5e-16": 1})
        sched.offer("j", "v5e-16", 1)
        assert sched.queue_wait_p50_s() is not None


class TestMultiSlice:
    def test_megascale_env(self, cluster):
        kube, _, ctl = cluster
        kube.create_custom(make_cr(num_slices=2))
        ctl.reconcile_once(kube.list_custom()[0])
        pods = kube.list_pods("kubeflow")
        assert len(pods) == 8  # 2 slices x 4 hosts
        env = {e["name"]: e["value"]
               for e in kube.get_pod("kubeflow", "train-worker-5")
               ["spec"]["containers"][0]["env"]}
        assert env["MEGASCALE_NUM_SLICES"] == "2"
        assert env["MEGASCALE_SLICE_ID"] == "1"


class TestUnsatisfiableJobs:
    def test_unsatisfiable_job_fails_fast(self, cluster):
        """Demand beyond total inventory -> Failed/UnsatisfiableResources,
        not Queued forever (the reference had no admission check at all)."""
        kube, sched, ctl = cluster
        kube.create_custom(make_cr(name="huge", num_slices=5))  # cap is 2
        cr = kube.list_custom()[0]
        assert ctl.reconcile_once(cr) == JOB_FAILED
        assert cr["status"]["reason"] == "UnsatisfiableResources"
        assert "capacity" in cr["status"]["message"]
        # Released from the queue: nothing left pending.
        assert sched.position("kubeflow/huge") is None

    def test_unsatisfiable_head_does_not_wedge_queue(self, cluster):
        """A failed unsatisfiable head unblocks later jobs in FIFO order."""
        kube, sched, ctl = cluster
        kube.create_custom(make_cr(name="huge", num_slices=5))
        kube.create_custom(make_cr(name="ok", num_slices=1))
        ctl.reconcile_all()
        crs = {c["metadata"]["name"]: c for c in kube.list_custom()}
        assert crs["huge"]["status"]["phase"] == JOB_FAILED
        # Second pass: with the head gone, "ok" is admitted and starts.
        ctl.reconcile_all()
        assert crs["ok"]["status"]["phase"] == STARTING


class TestNodeQuarantine:
    """Bad-node attribution: repeated WorkerFailed pods on one node
    quarantine it — excluded from gang placement (anti-affinity on
    re-placed pods), event recorded, gauge exported, cooldown on the
    skewable policy clock."""

    def make_controller(self, threshold=2, window_s=600,
                        cooldown_s=1800):
        from kubeflow_tpu.operator.gang import NodeQuarantine

        kube = FakeKube()
        ctl = TPUJobController(
            kube, GangScheduler({"v5e-16": 2}),
            quarantine=NodeQuarantine(threshold=threshold,
                                      window_s=window_s,
                                      cooldown_s=cooldown_s))
        kube.create_custom(make_cr())
        return kube, ctl, kube.list_custom()[0]

    def flap_once(self, kube, ctl, cr, node="node-bad"):
        ctl.reconcile_once(cr)
        for pod in kube.list_pods("kubeflow"):
            kube.set_pod_node("kubeflow", pod["metadata"]["name"],
                              node)
            kube.set_pod_phase("kubeflow", pod["metadata"]["name"],
                               RUNNING)
        ctl.reconcile_once(cr)
        victim = kube.list_pods("kubeflow")[0]["metadata"]["name"]
        kube.set_pod_phase("kubeflow", victim, FAILED)
        ctl.reconcile_once(cr)  # gang restart, failure attributed

    def test_threshold_failures_quarantine_node(self):
        from kubeflow_tpu.testing import faults

        with faults.injected("seed=0"):
            kube, ctl, cr = self.make_controller(threshold=2)
            self.flap_once(kube, ctl, cr)
            assert ctl.quarantine.quarantined() == []
            self.flap_once(kube, ctl, cr)
            assert ctl.quarantine.quarantined() == ["node-bad"]
            events = [e for e in kube.events
                      if e["reason"] == "NodeQuarantined"]
            assert len(events) == 1
            assert "node-bad" in events[0]["involvedObject"]

    def test_replaced_gang_excludes_quarantined_node(self):
        from kubeflow_tpu.testing import faults

        with faults.injected("seed=0"):
            kube, ctl, cr = self.make_controller(threshold=2)
            self.flap_once(kube, ctl, cr)
            self.flap_once(kube, ctl, cr)
            ctl.reconcile_once(cr)  # re-place the gang
            pods = kube.list_pods("kubeflow")
            assert pods
            for pod in pods:
                terms = (pod["spec"]["affinity"]["nodeAffinity"]
                         ["requiredDuringSchedulingIgnoredDuring"
                          "Execution"]["nodeSelectorTerms"])
                expr = terms[0]["matchExpressions"][0]
                assert expr == {"key": "kubernetes.io/hostname",
                                "operator": "NotIn",
                                "values": ["node-bad"]}

    def test_healthy_placement_has_no_affinity(self):
        kube = FakeKube()
        ctl = TPUJobController(kube, GangScheduler({"v5e-16": 2}))
        kube.create_custom(make_cr())
        ctl.reconcile_once(kube.list_custom()[0])
        for pod in kube.list_pods("kubeflow"):
            assert "affinity" not in pod["spec"]

    def test_cooldown_expires_on_policy_clock(self):
        from kubeflow_tpu.testing import faults

        with faults.injected("seed=0") as inj:
            kube, ctl, cr = self.make_controller(threshold=2,
                                                 cooldown_s=300)
            self.flap_once(kube, ctl, cr)
            self.flap_once(kube, ctl, cr)
            assert ctl.quarantine.is_quarantined("node-bad")
            inj.advance_clock(301)
            assert not ctl.quarantine.is_quarantined("node-bad")
            ctl.reconcile_once(cr)
            for pod in kube.list_pods("kubeflow"):
                assert "affinity" not in pod["spec"]

    def test_window_prunes_stale_failures(self):
        from kubeflow_tpu.testing import faults

        with faults.injected("seed=0") as inj:
            kube, ctl, cr = self.make_controller(threshold=2,
                                                 window_s=60)
            self.flap_once(kube, ctl, cr)
            inj.advance_clock(120)  # first failure ages out
            self.flap_once(kube, ctl, cr)
            assert ctl.quarantine.quarantined() == []

    def test_unattributed_failures_never_quarantine(self):
        """Pods without spec.nodeName (unscheduled) blame nobody."""
        from kubeflow_tpu.testing import faults

        with faults.injected("seed=0"):
            kube = FakeKube()
            ctl = TPUJobController(kube, GangScheduler({"v5e-16": 2}))
            kube.create_custom(make_cr())
            cr = kube.list_custom()[0]
            for _ in range(4):
                ctl.reconcile_once(cr)
                set_all_pods(kube, "kubeflow", RUNNING)
                ctl.reconcile_once(cr)
                pod = kube.list_pods("kubeflow")[0]
                kube.set_pod_phase("kubeflow",
                                   pod["metadata"]["name"], FAILED)
                ctl.reconcile_once(cr)
            assert ctl.quarantine.quarantined() == []

    def test_gauge_exported_on_sweep(self):
        from kubeflow_tpu.runtime.prom import (
            REGISTRY,
            parse_metrics,
            sample_value,
        )
        from kubeflow_tpu.testing import faults

        with faults.injected("seed=0"):
            kube, ctl, cr = self.make_controller(threshold=2)
            self.flap_once(kube, ctl, cr)
            self.flap_once(kube, ctl, cr)
            ctl.reconcile_all()
            parsed = parse_metrics(REGISTRY.render())
            assert sample_value(
                parsed, "kft_operator_quarantined_nodes") == 1

    def test_quarantine_counts_once_not_per_failure(self):
        from kubeflow_tpu.testing import faults

        with faults.injected("seed=0"):
            kube, ctl, cr = self.make_controller(threshold=2)
            for _ in range(4):  # keep flapping past the trip point
                self.flap_once(kube, ctl, cr)
            events = [e for e in kube.events
                      if e["reason"] == "NodeQuarantined"]
            assert len(events) == 1

    def test_lingering_failed_pod_attributes_once_per_generation(self):
        """A real apiserver keeps listing a Failed pod through its
        deletion grace: repeated sweeps over the SAME failure must
        count once toward quarantine, not once per sweep."""
        from kubeflow_tpu.operator.gang import NodeQuarantine
        from kubeflow_tpu.testing import faults

        with faults.injected("seed=0"):
            kube = FakeKube()
            ctl = TPUJobController(
                kube, GangScheduler({"v5e-16": 2}),
                quarantine=NodeQuarantine(threshold=3))
            kube.create_custom(make_cr())
            job = crd.TPUJobSpec.from_custom_resource(
                kube.list_custom()[0])
            pod = {"metadata": {"name": "train-worker-0"},
                   "spec": {"nodeName": "node-x"},
                   "status": {"phase": FAILED}}
            for _ in range(5):  # the same incident, sweep after sweep
                ctl._note_worker_failures(job, [pod], restarts=0)
            assert ctl.quarantine.quarantined() == []
            # A NEW generation (post-restart failure) counts again.
            ctl._note_worker_failures(job, [pod], restarts=1)
            ctl._note_worker_failures(job, [pod], restarts=2)
            assert ctl.quarantine.quarantined() == ["node-x"]
