"""Distributed request tracing: codec, span trees, tail sampling,
store bounds, the /debug/traces HTTP surface, and the keep-alive 404
guard for the /debug/* namespace (runtime/tracing.py + serving/http.py
+ fleet/router.py)."""

import http.client
import json
import threading
import time

import pytest

from kubeflow_tpu.runtime import tracing
from kubeflow_tpu.testing import faults


@pytest.fixture
def enabled_store():
    store = tracing.enable(sample_rate=1.0, capacity=16)
    try:
        yield store
    finally:
        tracing.disable()


class TestTraceparentCodec:
    def test_roundtrip(self):
        trace_id, span_id = tracing.new_trace_id(), tracing.new_span_id()
        header = tracing.format_traceparent(trace_id, span_id)
        parsed = tracing.parse_traceparent(header)
        assert parsed == (trace_id, span_id, 1)

    def test_unsampled_flag(self):
        header = tracing.format_traceparent("ab" * 16, "cd" * 8,
                                            sampled=False)
        assert tracing.parse_traceparent(header)[2] == 0

    @pytest.mark.parametrize("bad", [
        None, "", "garbage", "00-short-span-01",
        "00-" + "0" * 32 + "-" + "ab" * 8 + "-01",   # all-zero trace
        "00-" + "ab" * 16 + "-" + "0" * 16 + "-01",  # all-zero span
        "ff-" + "ab" * 16 + "-" + "cd" * 8 + "-01",  # reserved version
        "00-" + "zz" * 16 + "-" + "cd" * 8 + "-01",  # non-hex
    ])
    def test_malformed_is_none_not_raise(self, bad):
        assert tracing.parse_traceparent(bad) is None

    def test_extract_needs_enabled_tracer(self):
        tracing.disable()
        header = tracing.format_traceparent("ab" * 16, "cd" * 8)
        assert tracing.extract({"traceparent": header}) is None

    def test_extract_marks_context_remote(self, enabled_store):
        header = tracing.format_traceparent("ab" * 16, "cd" * 8)
        ctx = tracing.extract({"traceparent": header})
        assert ctx is not None and ctx.remote
        assert ctx.trace_id == "ab" * 16


class TestDisabledIsFree:
    def test_all_entry_points_noop(self):
        tracing.disable()
        span = tracing.start_span("x")
        assert span is tracing.NULL_SPAN
        assert not span
        span.annotate(a=1)
        span.end(status="error")
        assert tracing.current_ctx() is None
        assert tracing.record_span(
            "y", tracing.SpanContext("a" * 32, "b" * 16), 0.0, 1.0
        ) is None
        assert tracing.new_root_ctx() is None
        assert tracing.snapshot() == {"enabled": False, "traces": []}


class TestSpansAndSampling:
    def test_child_spans_share_trace_and_parent(self, enabled_store):
        root = tracing.start_span("root")
        child = tracing.start_span("child", parent=root)
        assert child.trace_id == root.trace_id
        assert child.parent_id == root.span_id
        child.end()
        root.end()
        traces = enabled_store.traces()
        assert len(traces) == 1
        spans = {s["name"]: s for s in traces[0]["spans"]}
        assert spans["child"]["parent_id"] == spans["root"]["span_id"]
        assert spans["root"]["parent_id"] is None

    def test_current_ctx_via_use_span(self, enabled_store):
        assert tracing.current_ctx() is None
        span = tracing.start_span("server")
        with tracing.use_span(span):
            ctx = tracing.current_ctx()
            assert ctx is not None
            assert ctx.span_id == span.span_id
        assert tracing.current_ctx() is None

    def test_remote_parent_makes_local_root(self, enabled_store):
        header = tracing.format_traceparent(tracing.new_trace_id(),
                                            tracing.new_span_id())
        ctx = tracing.extract({"traceparent": header})
        span = tracing.start_span("server.predict", parent=ctx)
        span.end(status="ok")
        # The local root's end completed the trace (sample_rate 1.0).
        assert len(enabled_store.traces()) == 1

    def test_error_always_retained_at_zero_sample_rate(self):
        store = tracing.enable(sample_rate=0.0)
        try:
            for _ in range(5):
                tracing.start_span("ok-request").end(status="ok")
            assert store.traces() == []
            tracing.start_span("bad-request").end(
                status="deadline_exceeded")
            traces = store.traces()
            assert len(traces) == 1
            assert traces[0]["retained"] == "error"
            assert traces[0]["status"] == "deadline_exceeded"
        finally:
            tracing.disable()

    def test_slow_traces_kept_by_rolling_threshold(self):
        store = tracing.TraceStore(sample_rate=0.0,
                                   min_slow_samples=4)
        for i in range(8):
            store.complete(f"{i:032x}", "ok", 0.01)
        assert len(store) == 0
        tid = "ab" * 16
        store.add({"trace_id": tid, "span_id": "cd" * 8,
                   "parent_id": None, "name": "slow", "start_s": 0.0,
                   "duration_ms": 2000.0, "status": "ok", "attrs": {}})
        assert store.complete(tid, "ok", 2.0) == "slow"
        assert store.traces()[0]["retained"] == "slow"

    def test_threshold_window_ages_on_policy_clock(self):
        with faults.injected("seed=1") as inj:
            store = tracing.TraceStore(sample_rate=0.0,
                                       min_slow_samples=4,
                                       slow_window_s=30.0)
            for i in range(8):
                store.complete(f"{i:032x}", "ok", 0.01)
            inj.advance_clock(60)  # the whole window expires
            # Below min samples again: nothing qualifies as slow.
            assert store.complete("ab" * 16, "ok", 5.0) is None

    def test_store_capacity_bounded(self):
        store = tracing.TraceStore(capacity=4, sample_rate=0.0)
        for i in range(10):
            store.complete(f"{i:032x}", "error", 0.01)
        assert len(store) == 4
        newest = store.traces()[0]["trace_id"]
        assert newest == f"{9:032x}"

    def test_spans_per_trace_bounded(self, enabled_store):
        enabled_store.max_spans_per_trace = 3
        root = tracing.start_span("root")
        for i in range(6):
            tracing.start_span(f"c{i}", parent=root).end()
        root.end()
        spans = enabled_store.traces()[0]["spans"]
        assert len(spans) == 3

    def test_late_spans_append_to_retained_trace(self, enabled_store):
        # The hermetic-fleet shape: the replica's local root completes
        # the trace first; the router's spans arrive after and must
        # still land in the kept entry.
        root = tracing.start_span("router.request")
        fwd = tracing.start_span("router.forward", parent=root)
        ctx = tracing.extract({"traceparent": fwd.traceparent()})
        server = tracing.start_span("server.predict", parent=ctx)
        server.end(status="ok")        # completes (sample_rate 1.0)
        fwd.end(status="ok")
        root.end(status="ok")
        traces = enabled_store.traces()
        assert len(traces) == 1
        names = {s["name"] for s in traces[0]["spans"]}
        assert names == {"router.request", "router.forward",
                         "server.predict"}

    def test_record_span_stamps_perf_readings(self, enabled_store):
        ctx = tracing.new_root_ctx()
        t0 = time.perf_counter()
        tracing.record_span("child", ctx, t0, t0 + 0.25,
                            attrs={"k": "v"})
        tracing.record_span("root", ctx, t0, t0 + 0.5, root=True)
        trace = enabled_store.traces()[0]
        spans = {s["name"]: s for s in trace["spans"]}
        assert spans["child"]["duration_ms"] == 250.0
        assert spans["child"]["parent_id"] == ctx.span_id
        assert spans["root"]["span_id"] == ctx.span_id
        assert spans["root"]["parent_id"] is None

    def test_trace_metrics_exported(self):
        from kubeflow_tpu.runtime.prom import REGISTRY, parse_metrics

        store = tracing.enable(sample_rate=0.0)
        try:
            tracing.start_span("boom").end(status="error")
            assert len(store) == 1
        finally:
            tracing.disable()
        parsed = parse_metrics(REGISTRY.render())
        assert "kft_trace_spans_total" in parsed
        assert any(labels.get("reason") == "error"
                   for labels, _ in parsed["kft_trace_retained_total"])
        assert "kft_trace_store_traces" in parsed


class TestDebugRoutes:
    """/debug/traces on the serving REST port + the keep-alive 404
    guard extended to the /debug/* namespace."""

    @pytest.fixture
    def http_server(self):
        from kubeflow_tpu.serving.http import make_http_server
        from kubeflow_tpu.serving.model_server import ModelServer

        server = ModelServer()
        httpd, _ = make_http_server(server, port=0, host="127.0.0.1")
        try:
            yield httpd.server_address[1]
        finally:
            httpd.shutdown()
            server.stop()

    def test_debug_traces_route(self, http_server):
        store = tracing.enable(sample_rate=1.0)
        try:
            tracing.start_span("probe").end()
            assert len(store) == 1
            conn = http.client.HTTPConnection("127.0.0.1", http_server,
                                              timeout=30)
            conn.request("GET", "/debug/traces")
            resp = conn.getresponse()
            payload = json.loads(resp.read())
            conn.close()
        finally:
            tracing.disable()
        assert resp.status == 200
        assert payload["enabled"] is True
        assert payload["traces"][0]["root"] == "probe"

    def test_debug_traces_disabled_still_answers(self, http_server):
        tracing.disable()
        conn = http.client.HTTPConnection("127.0.0.1", http_server,
                                          timeout=30)
        conn.request("GET", "/debug/traces")
        resp = conn.getresponse()
        payload = json.loads(resp.read())
        conn.close()
        assert resp.status == 200
        assert payload == {"enabled": False, "traces": []}

    def test_unknown_debug_route_404_and_keepalive_survives(
            self, http_server):
        # A POST with a body to an unknown /debug/* path must answer
        # 404 JSON with the body DRAINED: on this same keep-alive
        # connection an unread body would be parsed as the next
        # request line, desyncing everything after it.
        conn = http.client.HTTPConnection("127.0.0.1", http_server,
                                          timeout=30)
        body = json.dumps({"pad": "x" * 4096}).encode()
        conn.request("POST", "/debug/nonexistent", body=body)
        resp = conn.getresponse()
        payload = json.loads(resp.read())
        assert resp.status == 404
        assert "no route" in payload["error"]
        # Same connection, next request: still in sync.
        conn.request("GET", "/healthz")
        resp2 = conn.getresponse()
        assert resp2.status == 200
        assert json.loads(resp2.read())["status"] == "ok"
        conn.close()


class TestConcurrentStore:
    def test_parallel_span_recording_consistent(self, enabled_store):
        errors = []

        def worker(i):
            try:
                for j in range(20):
                    root = tracing.start_span(f"w{i}-{j}")
                    tracing.start_span("child", parent=root).end()
                    root.end(status="ok")
            except Exception as exc:  # noqa: BLE001 — surfaced below
                errors.append(exc)

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors
        # Capacity bound held under concurrency.
        assert len(enabled_store) <= enabled_store.capacity
        for trace in enabled_store.traces():
            assert len(trace["spans"]) <= 2


class TestJobLifecycleTraces:
    """operator/reconciler.py stamps one trace per TPUJob — a span per
    phase dwelled in, the root at the terminal transition — into the
    same tail-sampled store the serving path uses (served on the
    operator's metrics port)."""

    def _run_job(self, kube, controller, namespace="kubeflow-test"):
        from kubeflow_tpu.operator.kube import RUNNING, SUCCEEDED
        from kubeflow_tpu.operator.reconciler import (
            JOB_RUNNING,
            JOB_SUCCEEDED,
        )

        cr = kube.list_custom()[0]
        controller.reconcile_once(cr)
        for pod in kube.list_pods(namespace):
            kube.set_pod_phase(namespace, pod["metadata"]["name"],
                               RUNNING)
        assert controller.reconcile_once(cr) == JOB_RUNNING
        for pod in kube.list_pods(namespace):
            kube.set_pod_phase(namespace, pod["metadata"]["name"],
                               SUCCEEDED)
        assert controller.reconcile_once(cr) == JOB_SUCCEEDED

    def test_phase_spans_and_terminal_root(self, enabled_store):
        from kubeflow_tpu.operator import crd
        from kubeflow_tpu.operator.gang import GangScheduler
        from kubeflow_tpu.operator.kube import FakeKube
        from kubeflow_tpu.operator.reconciler import TPUJobController

        kube = FakeKube()
        controller = TPUJobController(kube, GangScheduler({"v5e-8": 1}))
        job = crd.TPUJobSpec(name="traced", namespace="kubeflow-test",
                             slice_type="v5e-8")
        kube.create_custom(job.to_custom_resource())
        self._run_job(kube, controller)
        traces = [t for t in enabled_store.traces()
                  if any(s["name"] == "job.lifecycle"
                         for s in t["spans"])]
        assert len(traces) == 1
        spans = {s["name"]: s for s in traces[0]["spans"]}
        assert {"job.Starting", "job.Running",
                "job.lifecycle"} <= set(spans)
        root = spans["job.lifecycle"]
        assert root["status"] == "ok"
        assert root["attrs"]["phase"] == "Succeeded"
        assert spans["job.Running"]["attrs"]["to"] == "Succeeded"
        assert spans["job.Starting"]["parent_id"] == root["span_id"]
        # Terminal jobs keep a DONE tombstone (pruned when the CR
        # vanishes): a later re-stamp of the same terminal phase must
        # not mint a second trace.
        tomb = controller._job_traces["kubeflow-test/traced"]
        assert tomb["done"] is True

    def test_failed_job_always_retained(self):
        from kubeflow_tpu.operator import crd
        from kubeflow_tpu.operator.gang import GangScheduler
        from kubeflow_tpu.operator.kube import FakeKube
        from kubeflow_tpu.operator.reconciler import TPUJobController

        store = tracing.enable(sample_rate=0.0)
        try:
            kube = FakeKube()
            controller = TPUJobController(kube,
                                          GangScheduler({"v5e-8": 1}))
            cr = crd.TPUJobSpec(
                name="bad", namespace="kubeflow-test",
                slice_type="v5e-8").to_custom_resource()
            cr["spec"]["sliceType"] = "not-a-slice"  # InvalidSpec
            kube.create_custom(cr)
            controller.reconcile_all()
            traces = store.traces()
            assert len(traces) == 1
            assert traces[0]["retained"] == "error"
            root = [s for s in traces[0]["spans"]
                    if s["name"] == "job.lifecycle"][0]
            assert root["attrs"]["phase"] == "Failed"
            assert root["attrs"]["reason"] == "InvalidSpec"
        finally:
            tracing.disable()

    def test_scheduler_plan_span_recorded(self, enabled_store):
        from kubeflow_tpu.operator.gang import GangScheduler
        from kubeflow_tpu.scheduler import ClusterScheduler

        cluster = ClusterScheduler(GangScheduler({"v5e-8": 1}))
        cluster.plan([])
        names = [t["root"] for t in enabled_store.traces()]
        assert "scheduler.plan" in names


class TestBatcherSpans:
    def test_queue_wait_and_dispatch_spans(self, enabled_store):
        import numpy as np

        from kubeflow_tpu.serving.model_server import MicroBatcher

        batcher = MicroBatcher(
            lambda inputs: {"y": np.asarray(inputs["x"]) + 1},
            max_batch_size=2, batch_timeout_s=0.001, name="traced")
        try:
            span = tracing.start_span("server.predict")
            with tracing.use_span(span):
                out = batcher.submit({"x": np.zeros((1, 2))})
            span.end()
        finally:
            batcher.close()
        np.testing.assert_allclose(out["y"], 1.0)
        trace = enabled_store.traces()[0]
        spans = {s["name"]: s for s in trace["spans"]}
        assert {"batcher.queue_wait", "batcher.dispatch",
                "server.predict"} <= set(spans)
        assert spans["batcher.dispatch"]["attrs"]["batcher"] \
            == "traced"
        assert spans["batcher.queue_wait"]["parent_id"] \
            == spans["server.predict"]["span_id"]

    def test_untraced_submissions_record_nothing(self, enabled_store):
        import numpy as np

        from kubeflow_tpu.serving.model_server import MicroBatcher

        batcher = MicroBatcher(
            lambda inputs: {"y": np.asarray(inputs["x"])},
            max_batch_size=2, batch_timeout_s=0.001, name="quiet")
        try:
            # No current span context: entries carry trace=None and no
            # span site fires, even with the tracer globally enabled.
            batcher.submit({"x": np.zeros((1, 2))})
        finally:
            batcher.close()
        assert enabled_store.traces() == []


class TestReviewRegressions:
    def test_extract_case_insensitive_on_plain_dicts(
            self, enabled_store):
        # HTTP header names are case-insensitive on the wire and
        # proxies commonly re-case them; the router hands extract() a
        # plain dict with the sender's casing preserved.
        header = tracing.format_traceparent("ab" * 16, "cd" * 8)
        ctx = tracing.extract({"Traceparent": header})
        assert ctx is not None and ctx.trace_id == "ab" * 16

    def test_slow_windows_are_per_root_name(self):
        # One store holds heterogeneous trace kinds: a fast kind's
        # rolling window (e.g. scheduler.plan micro-passes) must not
        # set the threshold a slow kind (job.lifecycle) is judged
        # against — that would retain 100% of healthy slow-kind
        # traces as "slow", defeating the sample-rate knob.
        store = tracing.TraceStore(sample_rate=0.0,
                                   min_slow_samples=4)
        for i in range(32):
            store.complete(f"{i:032x}", "ok", 0.0001,
                           name="scheduler.plan")
        assert store.complete("ab" * 16, "ok", 30.0,
                              name="job.lifecycle") is None
        # ...while within ONE name the threshold still works.
        for i in range(32, 48):
            store.complete(f"{i:032x}", "ok", 1.0,
                           name="job.lifecycle")
        assert store.complete("cd" * 16, "ok", 30.0,
                              name="job.lifecycle") == "slow"

    def test_router_crash_still_completes_trace_as_error(
            self, enabled_store, monkeypatch):
        from kubeflow_tpu.fleet.endpoints import (
            EndpointRegistry,
            StaticEndpoints,
        )
        from kubeflow_tpu.fleet.router import FleetRouter

        router = FleetRouter(
            EndpointRegistry(StaticEndpoints.from_urls([])))
        monkeypatch.setattr(
            router, "_route",
            lambda *a, **k: (_ for _ in ()).throw(
                RuntimeError("boom")))
        with pytest.raises(RuntimeError):
            router.handle("POST", "/model/lm:predict", b"{}", {})
        traces = enabled_store.traces()
        assert len(traces) == 1
        assert traces[0]["status"] == "error"
        assert traces[0]["retained"] == "error"

    def test_deleted_job_trace_state_pruned(self, enabled_store):
        from kubeflow_tpu.operator import crd
        from kubeflow_tpu.operator.gang import GangScheduler
        from kubeflow_tpu.operator.kube import FakeKube
        from kubeflow_tpu.operator.reconciler import TPUJobController

        kube = FakeKube()
        controller = TPUJobController(kube, GangScheduler({"v5e-8": 1}))
        job = crd.TPUJobSpec(name="doomed", namespace="kubeflow-test",
                             slice_type="v5e-8")
        kube.create_custom(job.to_custom_resource())
        controller.reconcile_all()  # Queued/Starting — non-terminal
        assert "kubeflow-test/doomed" in controller._job_traces
        # CR deleted mid-run: no terminal transition will ever come.
        kube.delete_custom("kubeflow-test", "doomed")
        controller.reconcile_all()
        assert controller._job_traces == {}


class TestSecondReviewRegressions:
    def test_invalid_cr_stamps_one_trace_not_one_per_sweep(self):
        # A permanently invalid CR re-enters the Failed path EVERY
        # reconcile sweep (spec parse fails before the terminal
        # short-circuit); one bad CR must not LRU-flush the operator
        # store with a fresh error-retained trace per sweep.
        from kubeflow_tpu.operator import crd
        from kubeflow_tpu.operator.gang import GangScheduler
        from kubeflow_tpu.operator.kube import FakeKube
        from kubeflow_tpu.operator.reconciler import TPUJobController

        store = tracing.enable(sample_rate=0.0)
        try:
            kube = FakeKube()
            controller = TPUJobController(kube,
                                          GangScheduler({"v5e-8": 1}))
            cr = crd.TPUJobSpec(
                name="bad", namespace="kubeflow-test",
                slice_type="v5e-8").to_custom_resource()
            cr["spec"]["sliceType"] = "not-a-slice"
            kube.create_custom(cr)
            for _ in range(5):
                controller.reconcile_all()
            assert len(store.traces()) == 1, [
                t["trace_id"] for t in store.traces()]
        finally:
            tracing.disable()

    def test_client_fault_statuses_sample_like_ok(self):
        # 404/400 answers are not incidents: at sample rate 0 they
        # keep NOTHING, while genuine error statuses still always
        # keep — a scanner probing junk model names must not evict
        # incident traces.
        store = tracing.enable(sample_rate=0.0)
        try:
            tracing.start_span("server.predict").end(
                status="not_found")
            tracing.start_span("server.predict").end(
                status="invalid_argument")
            assert store.traces() == []
            tracing.start_span("server.predict").end(status="shed")
            assert [t["retained"] for t in store.traces()] == ["error"]
        finally:
            tracing.disable()

    def test_http_unknown_model_trace_not_error_retained(self):
        import urllib.error
        import urllib.request

        from kubeflow_tpu.serving.http import make_http_server
        from kubeflow_tpu.serving.model_server import ModelServer

        server = ModelServer()
        httpd, _ = make_http_server(server, port=0, host="127.0.0.1")
        store = tracing.enable(sample_rate=0.0)
        try:
            port = httpd.server_address[1]
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/model/nope:predict",
                data=b'{"instances": [[1]]}')
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(req, timeout=30)
            assert err.value.code == 404
            assert store.traces() == [], (
                "a 404 answer must not ride the always-keep tier")
        finally:
            tracing.disable()
            httpd.shutdown()
            server.stop()


class TestRetentionPolicyRegressions:
    def test_eviction_prefers_sampled_over_error_traces(self):
        # Sustained healthy sampled traffic must not flush incident
        # traces out of the bounded store: on overflow, sampled
        # traces evict first, error-retained ones only when nothing
        # else remains.
        store = tracing.TraceStore(capacity=4, sample_rate=1.0)
        for i in range(2):
            store.complete(f"{i:032x}", "deadline_exceeded", 0.01)
        for i in range(2, 20):
            store.complete(f"{i:032x}", "ok", 0.01)
        kept = store.traces()
        errors = [t for t in kept if t["retained"] == "error"]
        assert len(kept) == 4
        assert len(errors) == 2, (
            f"healthy traffic evicted incident traces: "
            f"{[(t['trace_id'], t['retained']) for t in kept]}")

    def test_open_trace_age_refreshes_on_new_spans(self):
        # Aging reaps traces whose root will never complete; a trace
        # still ACCUMULATING spans is alive and must keep them all.
        with faults.injected("seed=1") as inj:
            store = tracing.TraceStore(sample_rate=1.0,
                                       max_open_age_s=100.0)
            ctx = tracing.SpanContext("ab" * 16, "cd" * 8)
            for i in range(5):
                store.add({"trace_id": ctx.trace_id,
                           "span_id": f"{i:016x}", "parent_id": None,
                           "name": f"s{i}", "start_s": 0.0,
                           "duration_ms": 1.0, "status": "ok",
                           "attrs": {}})
                inj.advance_clock(60)  # > age/5 apart, < age total
            store.complete(ctx.trace_id, "ok", 300.0)
            assert len(store.traces()[0]["spans"]) == 5

    def test_long_running_job_keeps_all_phase_spans(self):
        # The reconciler buffers phase spans in controller memory and
        # stamps the WHOLE trace at the terminal transition, so a job
        # Running far past the store's open-trace age still shows its
        # Queued/Starting/Running timeline.
        from kubeflow_tpu.operator import crd
        from kubeflow_tpu.operator.gang import GangScheduler
        from kubeflow_tpu.operator.kube import (
            RUNNING,
            SUCCEEDED,
            FakeKube,
        )
        from kubeflow_tpu.operator.reconciler import TPUJobController

        with faults.injected("seed=1") as inj:
            store = tracing.enable(sample_rate=1.0,
                                   max_open_age_s=60.0)
            try:
                kube = FakeKube()
                controller = TPUJobController(
                    kube, GangScheduler({"v5e-8": 1}))
                job = crd.TPUJobSpec(name="marathon",
                                     namespace="kubeflow-test",
                                     slice_type="v5e-8")
                kube.create_custom(job.to_custom_resource())
                cr = kube.list_custom()[0]
                controller.reconcile_once(cr)
                for pod in kube.list_pods("kubeflow-test"):
                    kube.set_pod_phase("kubeflow-test",
                                       pod["metadata"]["name"],
                                       RUNNING)
                controller.reconcile_once(cr)
                # The job runs WAY past the open-trace age (policy
                # clock; other traffic may sweep the open buffer).
                inj.advance_clock(7200)
                store.complete("ff" * 16, "ok", 0.01)  # sweep trigger
                for pod in kube.list_pods("kubeflow-test"):
                    kube.set_pod_phase("kubeflow-test",
                                       pod["metadata"]["name"],
                                       SUCCEEDED)
                controller.reconcile_once(cr)
                trace = next(
                    t for t in store.traces()
                    if any(s["name"] == "job.lifecycle"
                           for s in t["spans"]))
                names = {s["name"] for s in trace["spans"]}
                assert {"job.Starting", "job.Running",
                        "job.lifecycle"} <= names, names
            finally:
                tracing.disable()


class TestErroredRootUnderDroppedId:
    def test_error_outranks_drop_memory(self):
        # A client reusing ONE traceparent across requests: request 1
        # samples out (trace_id lands in the drop memory), request 2
        # errors under the same id — the always-keep tier must still
        # capture it.
        store = tracing.enable(sample_rate=0.0)
        try:
            header = tracing.format_traceparent("ab" * 16, "cd" * 8)
            ctx = tracing.extract({"traceparent": header})
            tracing.start_span("server.predict", parent=ctx).end(
                status="ok")          # dropped (rate 0)
            assert store.traces() == []
            tracing.start_span("server.predict", parent=ctx).end(
                status="deadline_exceeded")
            kept = store.traces()
            assert len(kept) == 1
            assert kept[0]["retained"] == "error"
            assert kept[0]["trace_id"] == "ab" * 16
        finally:
            tracing.disable()
