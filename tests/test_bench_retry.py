"""bench.py backend-acquisition resilience.

Round 3's driver capture failed with rc=1 because one transient
``UNAVAILABLE`` from the tunneled TPU backend escaped the bare
``jax.devices()`` call (VERDICT round 3, item 1).  These tests pin the
fix: a bounded retry that survives transient failures, resets the cached
backend between attempts, and degrades to a single parseable JSON
failure record when the backend never comes up.
"""

import json
import os

import bench


class _FlakyBackend:
    """Fails n times, then succeeds — the tunnel flake in miniature."""

    def __init__(self, failures, devices=("dev0",)):
        self.failures = failures
        self.calls = 0
        self.devices = list(devices)

    def __call__(self):
        self.calls += 1
        if self.calls <= self.failures:
            raise RuntimeError("UNAVAILABLE: TPU backend setup/compile error")
        return self.devices


def test_retry_survives_two_transient_failures():
    backend = _FlakyBackend(failures=2)
    sleeps = []
    resets = []
    devices, failure = bench.acquire_devices(
        backend, attempts=5, delays=(1, 2, 4),
        sleep=sleeps.append, reset=lambda: resets.append(1),
        log=lambda m: None)
    assert failure is None
    assert devices == ["dev0"]
    assert backend.calls == 3
    # Backed off before each retry, and reset the cached backend so the
    # retry is real rather than a replay of the cached error.
    assert sleeps == [1, 2]
    assert len(resets) == 2


def test_exhausted_retry_returns_structured_record():
    backend = _FlakyBackend(failures=99)
    devices, failure = bench.acquire_devices(
        backend, attempts=3, delays=(0,),
        sleep=lambda s: None, log=lambda m: None)
    assert devices is None
    assert backend.calls == 3
    # The record must be JSON-able and carry the one-line bench contract
    # fields so the driver's parser accepts it.
    line = json.loads(json.dumps(failure))
    assert line["metric"] == "backend_init_failed"
    assert {"metric", "value", "unit", "vs_baseline"} <= set(line)
    assert line["detail"]["attempts"] == 3
    assert len(line["detail"]["log"]) == 3
    assert "UNAVAILABLE" in line["detail"]["log"][0]


def test_reset_failure_is_nonfatal():
    backend = _FlakyBackend(failures=1)

    def bad_reset():
        raise ValueError("no cached backend")

    devices, failure = bench.acquire_devices(
        backend, attempts=2, delays=(0,), sleep=lambda s: None,
        reset=bad_reset, log=lambda m: None)
    assert failure is None
    assert devices == ["dev0"]


def test_delays_are_bounded():
    # The whole retry budget must stay within the driver's patience
    # (~3 minutes): sum of default delays < 180 s even though the last
    # delay repeats if attempts exceed the table.
    total = sum(bench.acquire_devices.__defaults__[1])
    assert total <= 180


def test_hung_acquisition_times_out_to_structured_record():
    """A wedged device grant makes jax.devices() HANG, not raise
    (observed live: a client killed mid-claim wedges the chip and every
    later acquisition blocks forever).  The watchdog must convert the
    hang into a normal failed attempt."""
    import threading

    never = threading.Event()

    def hang_forever():
        never.wait()  # blocks until test teardown; daemon thread

    devices, failure = bench.acquire_devices(
        hang_forever, attempts=2, delays=(0,), sleep=lambda s: None,
        log=lambda m: None, attempt_timeout_s=0.1)
    assert devices is None
    assert failure["metric"] == "backend_init_failed"
    assert "hung" in failure["detail"]["log"][0]
    never.set()


def test_watchdog_passes_through_success_and_errors():
    devices, failure = bench.acquire_devices(
        lambda: ["dev"], attempts=1, log=lambda m: None,
        attempt_timeout_s=5.0)
    assert failure is None and devices == ["dev"]

    def boom():
        raise RuntimeError("UNAVAILABLE")

    devices, failure = bench.acquire_devices(
        boom, attempts=2, delays=(0,), sleep=lambda s: None,
        log=lambda m: None, attempt_timeout_s=5.0)
    assert devices is None
    assert len(failure["detail"]["log"]) == 2


def test_soft_deadline_skips_tail_but_prints_headline(monkeypatch, capsys):
    """A driver-side hard timeout mid-suite records NOTHING (the one
    JSON line prints at the end); the soft deadline must skip remaining
    sub-benches and still deliver the headline record."""
    import sys as _sys

    monkeypatch.setenv("KFT_BENCH_DEADLINE_S", "0.000001")
    # main() appends the fake-device flag to XLA_FLAGS in-place; pin the
    # var so the append is rolled back after the test (subprocess-
    # spawning tests inherit os.environ).
    monkeypatch.setenv("XLA_FLAGS", os.environ.get("XLA_FLAGS", ""))
    monkeypatch.setattr(_sys, "argv", ["bench.py", "--model", "both",
                                       "--fake-devices", "8"])
    headline = {"metric": "resnet50_images_per_sec_per_chip",
                "value": 1.0, "unit": "x", "vs_baseline": 0.0,
                "detail": {}}
    monkeypatch.setattr(bench, "bench_resnet",
                        lambda *a, **k: dict(headline, detail={}))

    def boom(*a, **k):
        raise AssertionError("sub-bench ran past the deadline")

    for name in ("bench_lm", "bench_serving", "bench_lm_decode",
                 "bench_lm_engine", "bench_data", "bench_hfta",
                 "bench_colocation"):
        monkeypatch.setattr(bench, name, boom)
    monkeypatch.setattr(
        bench, "acquire_devices",
        lambda *a, **k: ([type("D", (), {"platform": "cpu"})()], None))
    bench.main()
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == 1, out
    record = json.loads(out[0])
    assert record["metric"] == "resnet50_images_per_sec_per_chip"
    assert set(record["detail"]["skipped_sub_benches"]) == {
        "lm", "lm_moe", "serving", "lm_decode", "lm_decode_int8",
        "lm_engine", "data", "hfta", "colocation"}


def _both_result():
    """A round-4-shaped --model=both record (driver tail, BENCH_r04)."""
    return {
        "metric": "resnet50_images_per_sec_per_chip", "value": 411.2,
        "unit": "images/sec/chip", "vs_baseline": 0.8,
        "detail": {
            "images_per_sec": 411.2, "step_time_ms": 218.0, "mfu": 0.34,
            "device": "TPU v5 lite",
            "roofline": {"frac_of_roofline": 0.91},
            "lm": {"value": 38000, "mfu": 0.55, "seq_len": 2048,
                   "step_time_ms": 430, "attention": "flash"},
            "lm_moe": {"value": 41000, "mfu": 0.432, "seq_len": 2048,
                       "moe_experts": 4, "optimizer": "adafactor"},
            "serving": {
                "sustained_ms_per_request": 1.41,
                "batcher_capacity_requests_per_sec": 142.6,
                "batcher_small_image": {"requests_per_sec": 482.4},
                # ballast standing in for the fields that overflowed
                # the driver tail in round 4
                "batcher_batch_size_hist": {str(i): i for i in range(64)},
            },
            "lm_decode": {"batched_tokens_per_sec": 3479.5,
                          "filler": "x" * 1200},
            "lm_decode_int8": {"batched_tokens_per_sec": 4058.0},
            "data": {"pipeline_native_examples_per_sec": 63962.0,
                     "native_vs_python_ratio": 1.77},
        },
    }


def test_headline_summary_fits_driver_tail():
    """Round 4's driver artifact recorded ``parsed: null`` because the
    single stdout line exceeded the 2000-char tail.  The summary must
    carry every north-star metric and fit with room to spare."""
    summary = bench.headline_summary(_both_result())
    line = json.dumps(summary)
    assert len(line) < 1500
    d = summary["detail"]
    assert summary["value"] == 411.2
    assert d["resnet_mfu"] == 0.34
    assert d["resnet_roofline_frac"] == 0.91
    assert d["lm_mfu"] == 0.55
    assert d["moe_mfu"] == 0.432
    assert d["decode_tokens_per_sec"] == 3479.5
    assert d["decode_tokens_per_sec_int8"] == 4058.0
    assert d["serving_batcher_capacity_req_s"] == 142.6
    assert d["serving_small_image_req_s"] == 482.4
    assert d["data_native_vs_python"] == 1.77
    assert d["full_results"] == "artifacts/bench_full.json"


def test_emit_big_record_compacts_stdout_keeps_full_blob(
        tmp_path, monkeypatch, capsys):
    monkeypatch.chdir(tmp_path)
    result = _both_result()
    bench.emit(result)
    captured = capsys.readouterr()
    lines = captured.out.strip().splitlines()
    assert len(lines) == 1
    assert len(lines[0]) < 2000
    assert json.loads(lines[0])["detail"]["moe_mfu"] == 0.432
    full = json.loads((tmp_path / "artifacts/bench_full.json").read_text())
    assert full == result
    assert "FULL RESULT:" in captured.err


def test_emit_big_single_model_record_keeps_scalar_detail(
        tmp_path, monkeypatch, capsys):
    """A large --model=serving record is NOT both-shaped; emit must keep
    its scalar metrics on stdout and drop only the oversized values."""
    monkeypatch.chdir(tmp_path)
    record = {
        "metric": "serving_predict_sustained_ms", "value": 1.4,
        "unit": "ms/request", "detail": {
            "batcher_capacity_requests_per_sec": 173.5,
            "wire_ceiling_req_s": 204.2,
            "device_ms_per_batch16": 0.26,
            "batcher_batch_size_hist": {str(i): i for i in range(400)},
        },
    }
    bench.emit(record)
    lines = capsys.readouterr().out.strip().splitlines()
    assert len(lines) == 1 and len(lines[0]) < 2000
    d = json.loads(lines[0])["detail"]
    assert d["batcher_capacity_requests_per_sec"] == 173.5
    assert d["wire_ceiling_req_s"] == 204.2
    assert d["device_ms_per_batch16"] == 0.26
    assert d["truncated_keys"] == ["batcher_batch_size_hist"]
    assert d["full_results"] == "artifacts/bench_full.json"


def test_emit_small_record_passes_through(tmp_path, monkeypatch, capsys):
    monkeypatch.chdir(tmp_path)
    record = {"metric": "m", "value": 1.0, "unit": "x", "vs_baseline": 0.0,
              "detail": {}}
    bench.emit(record)
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == 1
    assert json.loads(out[0]) == record
