"""bench.py backend-acquisition resilience.

Round 3's driver capture failed with rc=1 because one transient
``UNAVAILABLE`` from the tunneled TPU backend escaped the bare
``jax.devices()`` call (VERDICT round 3, item 1).  These tests pin the
fix: a bounded retry that survives transient failures, resets the cached
backend between attempts, and degrades to a single parseable JSON
failure record when the backend never comes up.
"""

import json

import bench


class _FlakyBackend:
    """Fails n times, then succeeds — the tunnel flake in miniature."""

    def __init__(self, failures, devices=("dev0",)):
        self.failures = failures
        self.calls = 0
        self.devices = list(devices)

    def __call__(self):
        self.calls += 1
        if self.calls <= self.failures:
            raise RuntimeError("UNAVAILABLE: TPU backend setup/compile error")
        return self.devices


def test_retry_survives_two_transient_failures():
    backend = _FlakyBackend(failures=2)
    sleeps = []
    resets = []
    devices, failure = bench.acquire_devices(
        backend, attempts=5, delays=(1, 2, 4),
        sleep=sleeps.append, reset=lambda: resets.append(1),
        log=lambda m: None)
    assert failure is None
    assert devices == ["dev0"]
    assert backend.calls == 3
    # Backed off before each retry, and reset the cached backend so the
    # retry is real rather than a replay of the cached error.
    assert sleeps == [1, 2]
    assert len(resets) == 2


def test_exhausted_retry_returns_structured_record():
    backend = _FlakyBackend(failures=99)
    devices, failure = bench.acquire_devices(
        backend, attempts=3, delays=(0,),
        sleep=lambda s: None, log=lambda m: None)
    assert devices is None
    assert backend.calls == 3
    # The record must be JSON-able and carry the one-line bench contract
    # fields so the driver's parser accepts it.
    line = json.loads(json.dumps(failure))
    assert line["metric"] == "backend_init_failed"
    assert {"metric", "value", "unit", "vs_baseline"} <= set(line)
    assert line["detail"]["attempts"] == 3
    assert len(line["detail"]["log"]) == 3
    assert "UNAVAILABLE" in line["detail"]["log"][0]


def test_reset_failure_is_nonfatal():
    backend = _FlakyBackend(failures=1)

    def bad_reset():
        raise ValueError("no cached backend")

    devices, failure = bench.acquire_devices(
        backend, attempts=2, delays=(0,), sleep=lambda s: None,
        reset=bad_reset, log=lambda m: None)
    assert failure is None
    assert devices == ["dev0"]


def test_delays_are_bounded():
    # The whole retry budget must stay within the driver's patience
    # (~3 minutes): sum of default delays < 180 s even though the last
    # delay repeats if attempts exceed the table.
    total = sum(bench.acquire_devices.__defaults__[1])
    assert total <= 180


def test_hung_acquisition_times_out_to_structured_record():
    """A wedged device grant makes jax.devices() HANG, not raise
    (observed live: a client killed mid-claim wedges the chip and every
    later acquisition blocks forever).  The watchdog must convert the
    hang into a normal failed attempt."""
    import threading

    never = threading.Event()

    def hang_forever():
        never.wait()  # blocks until test teardown; daemon thread

    devices, failure = bench.acquire_devices(
        hang_forever, attempts=2, delays=(0,), sleep=lambda s: None,
        log=lambda m: None, attempt_timeout_s=0.1)
    assert devices is None
    assert failure["metric"] == "backend_init_failed"
    assert "hung" in failure["detail"]["log"][0]
    never.set()


def test_watchdog_passes_through_success_and_errors():
    devices, failure = bench.acquire_devices(
        lambda: ["dev"], attempts=1, log=lambda m: None,
        attempt_timeout_s=5.0)
    assert failure is None and devices == ["dev"]

    def boom():
        raise RuntimeError("UNAVAILABLE")

    devices, failure = bench.acquire_devices(
        boom, attempts=2, delays=(0,), sleep=lambda s: None,
        log=lambda m: None, attempt_timeout_s=5.0)
    assert devices is None
    assert len(failure["detail"]["log"]) == 2
