"""The quickstart example must stay executable — it is the first thing
a new user runs (train -> checkpoint -> export -> serve -> query in one
file; docs/user_guide.md section 1)."""

import os
import pathlib
import subprocess
import sys
import pytest

REPO = pathlib.Path(__file__).parents[1]


def test_quickstart_end_to_end():
    env = dict(
        os.environ,
        # Hermetic spawn: CPU fake slice, no environment-injected jax
        # plugin paths (same rationale as test_serving_process.py).
        PYTHONPATH=str(REPO),
    )
    env.pop("JAX_PLATFORMS", None)       # the script pins cpu itself
    env.pop("KFT_QUICKSTART_TPU", None)  # never grab a host's real chip
    proc = subprocess.run(
        [sys.executable, str(REPO / "examples" / "quickstart.py")],
        capture_output=True, text=True, timeout=280, env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "quickstart OK" in proc.stdout
    # All four stages reported.
    for stage in ("[1]", "[2]", "[3]", "[4]"):
        assert stage in proc.stdout, proc.stdout


@pytest.mark.slow  # ~30s subprocess sweep of every parallelism family
def test_parallelism_tour_runs_every_family():
    """examples/parallelism.py: the SAME flagship model trains through
    dp/fsdp/tp/sp/ep/pp — the one-file proof of the mesh story the
    reference spread across three job kinds."""
    env = dict(os.environ, PYTHONPATH=str(REPO))
    env.pop("JAX_PLATFORMS", None)        # the script pins cpu itself
    env.pop("KFT_PARALLELISM_TPU", None)  # never grab a host's chip
    proc = subprocess.run(
        [sys.executable, str(REPO / "examples" / "parallelism.py")],
        capture_output=True, text=True, timeout=580, env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "tour complete" in proc.stdout
    for family in ("data-parallel", "fsdp", "tensor-parallel",
                   "sequence-parallel", "expert-parallel",
                   "pipeline-parallel"):
        assert family in proc.stdout, proc.stdout
