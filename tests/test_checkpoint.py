"""Checkpoint integrity: manifests, verification, walk-back, GC,
async-failure surfacing (runtime/checkpoint.py).

Pure-numpy states keep these fast; the Trainer-integrated resume path
is covered by test_train.py and the sharded/elastic contract by
TestElasticRestore here.
"""

import json
import threading
from pathlib import Path

import numpy as np
import pytest

from kubeflow_tpu.runtime.checkpoint import (
    CheckpointError,
    CheckpointManager,
    list_checkpoint_steps,
    manifest_path,
    verify_step,
)
from kubeflow_tpu.runtime.prom import REGISTRY, parse_metrics, sample_value
from kubeflow_tpu.testing import faults


def state_at(step):
    return {"step": np.full((), step, np.int32),
            "w": np.arange(8, dtype=np.float32) + step}


def fresh_like():
    return {"step": np.zeros((), np.int32),
            "w": np.zeros(8, np.float32)}


def save_steps(directory, steps, **kw):
    with CheckpointManager(directory, **kw) as mgr:
        for step in steps:
            assert mgr.save(step, state_at(step))


def corrupt_leaf(directory, step, nbytes=8):
    """Truncate the largest file of a step dir (a serialized leaf)."""
    step_dir = Path(directory) / str(step)
    victim = max((p for p in step_dir.rglob("*") if p.is_file()),
                 key=lambda p: p.stat().st_size)
    victim.write_bytes(victim.read_bytes()[:nbytes])
    return victim


def counter(name):
    return sample_value(parse_metrics(REGISTRY.render()), name) or 0.0


class TestManifest:
    def test_every_commit_writes_a_manifest(self, tmp_path):
        save_steps(tmp_path, [0, 1])
        for step in (0, 1):
            assert manifest_path(tmp_path, step).exists()
            ok, reason = verify_step(tmp_path, step)
            assert ok, reason

    def test_manifest_lists_files_and_leaves(self, tmp_path):
        save_steps(tmp_path, [0])
        with open(manifest_path(tmp_path, 0)) as f:
            manifest = json.load(f)
        assert manifest["step"] == 0
        assert manifest["files"]  # digests of everything the step wrote
        for entry in manifest["files"].values():
            assert entry["size"] > 0 and len(entry["blake2b"]) == 32
        paths = {leaf["path"] for leaf in manifest["leaves"]}
        assert any("w" in p for p in paths)

    def test_missing_manifest_fails_verification(self, tmp_path):
        save_steps(tmp_path, [0])
        manifest_path(tmp_path, 0).unlink()
        ok, reason = verify_step(tmp_path, 0)
        assert not ok and "manifest missing" in reason

    def test_corrupt_manifest_fails_verification(self, tmp_path):
        save_steps(tmp_path, [0])
        manifest_path(tmp_path, 0).write_text("{not json")
        ok, reason = verify_step(tmp_path, 0)
        assert not ok and "unreadable" in reason

    def test_truncated_leaf_fails_verification(self, tmp_path):
        save_steps(tmp_path, [0])
        corrupt_leaf(tmp_path, 0)
        ok, reason = verify_step(tmp_path, 0)
        assert not ok and ("truncated" in reason or "mismatch" in reason)

    def test_bitrot_fails_verification(self, tmp_path):
        save_steps(tmp_path, [0])
        step_dir = Path(tmp_path) / "0"
        victim = max((p for p in step_dir.rglob("*") if p.is_file()),
                     key=lambda p: p.stat().st_size)
        data = bytearray(victim.read_bytes())
        data[len(data) // 2] ^= 0xFF  # same size, flipped bit
        victim.write_bytes(bytes(data))
        ok, reason = verify_step(tmp_path, 0)
        assert not ok and "digest mismatch" in reason

    def test_extra_files_tolerated(self, tmp_path):
        save_steps(tmp_path, [0])
        (Path(tmp_path) / "0" / "sidecar.txt").write_text("x")
        ok, reason = verify_step(tmp_path, 0)
        assert ok, reason

    def test_list_checkpoint_steps(self, tmp_path):
        save_steps(tmp_path, [0, 2, 5])
        assert list_checkpoint_steps(tmp_path) == [0, 2, 5]
        assert list_checkpoint_steps(tmp_path / "nope") == []


class TestWalkBack:
    def test_kill_mid_save_resumes_from_verified_predecessor(
            self, tmp_path):
        """The acceptance scenario: an injected checkpoint.save fault
        kills the save between the orbax commit and the manifest —
        restore_or_init must land on the predecessor, never step 0,
        never the unverified latest."""
        with faults.injected("checkpoint.save:raise*1"):
            mgr = CheckpointManager(tmp_path)
            mgr.save(0, state_at(0))  # dies before its manifest
            with pytest.raises(CheckpointError):
                mgr.wait()
            mgr.save(1, state_at(1))
            mgr.save(2, state_at(2))
            mgr.wait()
            assert not manifest_path(tmp_path, 0).exists()
            # Kill the newest too: now 1 is the verified frontier.
            manifest_path(tmp_path, 2).unlink()
            restored, start = mgr.restore_or_init(fresh_like())
            assert start == 2
            np.testing.assert_allclose(restored["w"],
                                       state_at(1)["w"])
            mgr._mgr.close()

    def test_corrupt_latest_walks_back(self, tmp_path):
        save_steps(tmp_path, [0, 1, 2])
        corrupt_leaf(tmp_path, 2)
        before = counter("kft_checkpoint_verify_failures_total")
        with CheckpointManager(tmp_path) as mgr:
            restored, start = mgr.restore_or_init(fresh_like())
        assert start == 2
        np.testing.assert_allclose(restored["w"], state_at(1)["w"])
        assert counter("kft_checkpoint_verify_failures_total") > before

    def test_corrupt_manifest_walks_back(self, tmp_path):
        save_steps(tmp_path, [0, 1])
        manifest_path(tmp_path, 1).write_text("garbage")
        with CheckpointManager(tmp_path) as mgr:
            restored, start = mgr.restore_or_init(fresh_like())
        assert start == 1
        np.testing.assert_allclose(restored["w"], state_at(0)["w"])

    def test_everything_corrupt_starts_from_scratch(self, tmp_path):
        save_steps(tmp_path, [0, 1])
        corrupt_leaf(tmp_path, 0)
        corrupt_leaf(tmp_path, 1)
        with CheckpointManager(tmp_path) as mgr:
            state, start = mgr.restore_or_init(fresh_like())
        assert start == 0
        np.testing.assert_allclose(state["w"], np.zeros(8))

    def test_legacy_dir_without_manifests_still_resumes(self, tmp_path):
        """Pre-manifest checkpoint dirs (no manifest for ANY step)
        restore newest-first instead of being thrown away."""
        save_steps(tmp_path, [0, 1])
        for step in (0, 1):
            manifest_path(tmp_path, step).unlink()
        with CheckpointManager(tmp_path) as mgr:
            restored, start = mgr.restore_or_init(fresh_like())
        assert start == 2
        np.testing.assert_allclose(restored["w"], state_at(1)["w"])

    def test_latest_verified_step(self, tmp_path):
        save_steps(tmp_path, [0, 1, 2], max_to_keep=5)
        manifest_path(tmp_path, 2).unlink()
        with CheckpointManager(tmp_path, max_to_keep=5) as mgr:
            assert mgr.latest_step() == 2
            assert mgr.latest_verified_step() == 1


class TestGC:
    def test_keeps_max_to_keep(self, tmp_path):
        save_steps(tmp_path, [0, 1, 2, 3, 4], max_to_keep=2)
        assert list_checkpoint_steps(tmp_path) == [3, 4]
        # Manifests of deleted steps are gone too.
        assert not manifest_path(tmp_path, 0).exists()

    def test_never_deletes_last_verified_step(self, tmp_path):
        """Newer UNVERIFIED steps must not push the only restorable
        checkpoint out of the retention window."""
        mgr = CheckpointManager(tmp_path, max_to_keep=2)
        mgr.save(0, state_at(0))
        mgr.save(1, state_at(1))
        mgr.wait()
        with faults.injected("checkpoint.save:raise"):
            # Every further save dies pre-manifest.
            for step in (2, 3, 4):
                mgr.save(step, state_at(step))
                with pytest.raises(CheckpointError):
                    mgr.wait()
        steps = mgr.all_steps()
        assert 1 in steps, steps  # the verified survivor
        restored, start = mgr.restore_or_init(fresh_like())
        assert start == 2
        np.testing.assert_allclose(restored["w"], state_at(1)["w"])
        mgr._mgr.close()


class TestAsyncFailureSurfacing:
    def test_failure_surfaces_at_next_save(self, tmp_path):
        before = counter("kft_checkpoint_failures_total")
        with faults.injected("checkpoint.save:raise*1"):
            mgr = CheckpointManager(tmp_path)
            mgr.save(0, state_at(0))
            for t in list(mgr._threads):  # background finalize done
                t.join()
            with pytest.raises(CheckpointError):
                mgr.save(1, state_at(1))
            # Error consumed: the retry goes through and verifies.
            assert mgr.save(1, state_at(1))
            mgr.wait()
            assert verify_step(tmp_path, 1)[0]
            mgr._mgr.close()
        assert counter("kft_checkpoint_failures_total") == before + 1

    def test_failure_surfaces_at_wait(self, tmp_path):
        with faults.injected("checkpoint.save:raise*1"):
            mgr = CheckpointManager(tmp_path)
            mgr.save(0, state_at(0))
            with pytest.raises(CheckpointError):
                mgr.wait()
            mgr.wait()  # consumed: second wait is clean
            mgr._mgr.close()

    def test_saves_counted(self, tmp_path):
        before = counter("kft_checkpoint_saves_total")
        save_steps(tmp_path, [0, 1])
        assert counter("kft_checkpoint_saves_total") == before + 2

    def test_restore_hook_fires(self, tmp_path):
        save_steps(tmp_path, [0])
        with faults.injected("seed=0") as inj:
            with CheckpointManager(tmp_path) as mgr:
                mgr.restore_or_init(fresh_like())
            assert inj.fired("checkpoint.restore") == 1

    def test_concurrent_saves_all_finalize(self, tmp_path):
        """Finalize threads serialize on one lock; hammering saves
        from the main thread still yields a manifest per step."""
        with CheckpointManager(tmp_path, max_to_keep=10) as mgr:
            for step in range(6):
                mgr.save(step, state_at(step))
        for step in range(6):
            assert verify_step(tmp_path, step)[0], step


class TestElasticRestore:
    """Resuming on a different mesh layout than the one that saved —
    the abstract-target contract restore() has always promised."""

    def test_restore_across_mesh_layouts(self, tmp_path, devices):
        import jax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec

        mesh_a = Mesh(np.array(devices).reshape(8), ("data",))
        sharded = jax.device_put(
            np.arange(16, dtype=np.float32),
            NamedSharding(mesh_a, PartitionSpec("data")))
        save_steps_state = {"w": sharded,
                            "step": np.full((), 7, np.int32)}
        with CheckpointManager(tmp_path) as mgr:
            mgr.save(0, save_steps_state)

        # A "different slice shape": 2x4 mesh, w sharded over model.
        mesh_b = Mesh(np.array(devices).reshape(2, 4),
                      ("data", "model"))
        target = {
            "w": jax.ShapeDtypeStruct(
                (16,), np.float32,
                sharding=NamedSharding(mesh_b,
                                       PartitionSpec("model"))),
            "step": jax.ShapeDtypeStruct((), np.int32),
        }
        with CheckpointManager(tmp_path) as mgr2:
            assert mgr2.verify(0)
            restored = mgr2.restore(target, 0)
        np.testing.assert_allclose(np.asarray(restored["w"]),
                                   np.arange(16))
        assert restored["w"].sharding.mesh.shape == {"data": 2,
                                                     "model": 4}
        assert int(restored["step"]) == 7

    def test_typed_prng_keys_roundtrip(self, tmp_path):
        """The TrainState.rng leaf: typed keys are stored as raw key
        data and re-wrapped at restore (orbax cannot serialize
        extended key dtypes on every jax pairing)."""
        import jax

        key = jax.random.key(123)
        with CheckpointManager(tmp_path) as mgr:
            mgr.save(0, {"rng": key, "w": np.ones(4, np.float32)})
        with CheckpointManager(tmp_path) as mgr2:
            restored, start = mgr2.restore_or_init(
                {"rng": jax.random.key(0),
                 "w": np.zeros(4, np.float32)})
        assert start == 1
        assert jax.dtypes.issubdtype(restored["rng"].dtype,
                                     jax.dtypes.prng_key)
        np.testing.assert_array_equal(
            jax.random.key_data(restored["rng"]),
            jax.random.key_data(key))


class TestWaitSemantics:
    def test_wait_blocks_until_manifest_durable(self, tmp_path):
        mgr = CheckpointManager(tmp_path)
        mgr.save(0, state_at(0))
        mgr.wait()
        assert verify_step(tmp_path, 0)[0]
        mgr.close()

    def test_close_is_idempotent_under_threads(self, tmp_path):
        mgr = CheckpointManager(tmp_path)
        mgr.save(0, state_at(0))
        done = []
        t = threading.Thread(target=lambda: done.append(mgr.wait()))
        t.start()
        mgr.wait()
        t.join()
        mgr.close()


class TestReviewRegressions:
    def test_unreadable_file_fails_verification_not_crashes(
            self, tmp_path, monkeypatch):
        """An OSError while digesting a manifest-listed file is an
        unverifiable step, not a crash of the resume path."""
        import kubeflow_tpu.runtime.checkpoint as ckpt

        save_steps(tmp_path, [0])

        def boom(path):
            raise OSError("I/O error (bad sector)")

        monkeypatch.setattr(ckpt, "_digest_file", boom)
        ok, reason = verify_step(tmp_path, 0)
        assert not ok and "unreadable" in reason

    def test_intact_legacy_step_survives_manifested_corruption(
            self, tmp_path):
        """Upgrade scenario: legacy (manifest-less) steps OLDER than
        every manifested step stay restore candidates — a verified-
        but-unrestorable newest step walks back onto them instead of
        restarting from scratch."""
        from kubeflow_tpu.runtime.checkpoint import (
            _atomic_write_json,
            build_manifest,
        )

        save_steps(tmp_path, [0, 1, 2], max_to_keep=5)
        for step in (0, 1):  # pre-upgrade steps: no manifests
            manifest_path(tmp_path, step).unlink()
        # Newest step: payload rots AFTER the manifest is recomputed,
        # so verify passes but restore raises.
        corrupt_leaf(tmp_path, 2)
        _atomic_write_json(
            manifest_path(tmp_path, 2),
            build_manifest(Path(tmp_path) / "2", 2))
        with CheckpointManager(tmp_path, max_to_keep=5) as mgr:
            assert mgr.verify(2)  # manifest matches the rotten bytes
            restored, start = mgr.restore_or_init(fresh_like())
        assert start == 2, "legacy step 1 should have been restored"
        np.testing.assert_allclose(restored["w"], state_at(1)["w"])

    def test_died_mid_save_step_still_never_trusted(self, tmp_path):
        """The legacy carve-out must not weaken the kill-mid-save
        rule: a manifest-less step NEWER than a manifested one is a
        dead save, skipped."""
        save_steps(tmp_path, [0, 1], max_to_keep=5)
        manifest_path(tmp_path, 1).unlink()  # died before its manifest
        with CheckpointManager(tmp_path, max_to_keep=5) as mgr:
            restored, start = mgr.restore_or_init(fresh_like())
        assert start == 1
        np.testing.assert_allclose(restored["w"], state_at(0)["w"])

    def test_gc_runs_even_when_finalize_fails(self, tmp_path):
        """Persistent finalize failure (ENOSPC-class) must not also
        disable retention: step directories stay bounded at
        max_to_keep + the newest verified survivor."""
        mgr = CheckpointManager(tmp_path, max_to_keep=2)
        mgr.save(0, state_at(0))
        mgr.save(1, state_at(1))
        mgr.wait()
        with faults.injected("checkpoint.save:raise"):
            for step in range(2, 7):
                mgr.save(step, state_at(step))
                with pytest.raises(CheckpointError):
                    mgr.wait()
        steps = mgr.all_steps()
        assert len(steps) <= 3, steps  # newest 2 + verified survivor
        assert 1 in steps
        mgr._mgr.close()

    def test_finalize_skips_step_reclaimed_by_newer_gc(self, tmp_path):
        """A finalize that loses the race to a newer save's GC must
        not certify a vanished step (empty-file-map orphan manifest)."""
        import shutil

        before = counter("kft_checkpoint_saves_total")
        mgr = CheckpointManager(tmp_path, max_to_keep=2)
        mgr.save(0, state_at(0))
        mgr.wait()
        shutil.rmtree(Path(tmp_path) / "0")
        manifest_path(tmp_path, 0).unlink()
        mgr._finalize(0, [])  # the late, raced finalize
        assert not manifest_path(tmp_path, 0).exists()
        mgr.wait()  # no async error recorded either
        assert counter("kft_checkpoint_saves_total") == before + 1
        mgr._mgr.close()

    def test_gc_sweeps_orphan_manifests(self, tmp_path):
        save_steps(tmp_path, [0], max_to_keep=2)
        orphan = manifest_path(tmp_path, 9)
        orphan.write_text("{}")
        with CheckpointManager(tmp_path, max_to_keep=2) as mgr:
            mgr.save(1, state_at(1))
        assert not orphan.exists()
