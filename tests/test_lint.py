"""The lint gate runs as part of every test run — formatting-as-a-CI-step,
the reference's own policy (scripts/autoformat_jsonnet.sh:17-30,
build/check_boilerplate.sh via Makefile:15-18)."""

import pathlib
import subprocess
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent


class TestLintGate:
    def test_repo_is_lint_clean(self):
        proc = subprocess.run(
            [sys.executable, str(REPO / "ci" / "lint.py"), "--root",
             str(REPO)],
            capture_output=True, text=True,
        )
        assert proc.returncode == 0, (
            f"lint problems:\n{proc.stdout}\n{proc.stderr}")

    def test_deep_pass_runs_clean_on_repo(self):
        """PR-8: the semantic analyzer (clock/lock/jit/metric
        invariants) stays green with an empty shrink-only baseline."""
        proc = subprocess.run(
            [sys.executable, str(REPO / "ci" / "lint.py"), "--root",
             str(REPO), "--deep"],
            capture_output=True, text=True,
        )
        assert proc.returncode == 0, (
            f"deep lint problems:\n{proc.stdout}\n{proc.stderr}")
        assert "analysis:" in proc.stderr

    def test_deep_gate_catches_semantic_violations(self, tmp_path):
        """--deep must actually fire: a policy module reading the
        wall clock fails the combined gate even when classic lint
        passes."""
        bad = tmp_path / "kubeflow_tpu" / "serving"
        bad.mkdir(parents=True)
        (bad / "mod.py").write_text(
            '"""mod."""\nimport time\n\nD = time.monotonic() + 1\n')
        proc = subprocess.run(
            [sys.executable, str(REPO / "ci" / "lint.py"), "--root",
             str(tmp_path), "--deep"],
            capture_output=True, text=True,
        )
        assert proc.returncode == 1
        assert "clock-discipline" in proc.stdout

    def test_gate_catches_violations(self, tmp_path):
        """The gate must actually fire — a sabotaged tree fails."""
        bad = tmp_path / "kubeflow_tpu"
        bad.mkdir()
        (bad / "mod.py").write_text(
            "import datetime\n"
            "x = datetime.utcnow()  # TODO fix\n"
            "y = 1\t\n"
        )
        proc = subprocess.run(
            [sys.executable, str(REPO / "ci" / "lint.py"), "--root",
             str(tmp_path)],
            capture_output=True, text=True,
        )
        assert proc.returncode == 1
        assert "docstring required" in proc.stdout
        assert "banned" in proc.stdout
        assert "trailing whitespace" in proc.stdout
