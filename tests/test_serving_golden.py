"""Golden-output regression test for the serving path.

Heir of the reference's committed inference goldens: it shipped
result.txt from a real Inception Predict and diffed serving output
against it in CI (components/k8s-model-server/images/test-worker/
result.txt, testing/test_tf_serving.py).  Same idea here: a
deterministic Inception-v3 (fixed init seed, fixed input) is exported
through the real export/load/serve stack and its scores are diffed
against the committed artifact, so a release pipeline catches any
numerical or contract drift in export, loaders, or the HTTP layer.

Regenerate after an intentional model/serving change with:
    KFT_UPDATE_GOLDEN=1 python -m pytest tests/test_serving_golden.py
"""

import json
import os
from pathlib import Path

import numpy as np
import pytest

GOLDEN = Path(__file__).parent / "golden" / "inception_predict.json"
SEED = 20260730


@pytest.fixture(scope="module")
def served_api(tmp_path_factory):
    import jax

    from kubeflow_tpu.models.inception import InceptionV3
    from kubeflow_tpu.serving.export import export
    from kubeflow_tpu.serving.http import ServingAPI
    from kubeflow_tpu.serving.model_server import ModelServer

    base = tmp_path_factory.mktemp("models") / "inception"
    model = InceptionV3(num_classes=16)
    x = np.zeros((1, 96, 96, 3), np.float32)
    variables = model.init(jax.random.key(SEED), x, train=False)
    export(base, 1, variables,
           loader="kubeflow_tpu.serving.loaders:classifier",
           config={"family": "inception_v3", "num_classes": 16,
                   "top_k": 5},
           signature={"inputs": {"image": [None, 96, 96, 3]},
                      "outputs": {"scores": [None, 16]}})
    server = ModelServer()
    server.add_model("inception", str(base))
    return ServingAPI(server)


def _request_image():
    rng = np.random.RandomState(SEED)
    return rng.uniform(-1, 1, size=(1, 96, 96, 3)).astype(np.float32)


def test_predict_matches_golden(served_api):
    out = served_api.predict(
        "inception", {"instances": [{"image": _request_image()[0].tolist()}]})
    pred = out["predictions"][0]
    got = {
        "scores": np.asarray(pred["scores"], np.float64).round(6).tolist(),
        "top_k_classes": np.asarray(pred["top_k_classes"]).tolist(),
    }
    if os.environ.get("KFT_UPDATE_GOLDEN"):
        GOLDEN.parent.mkdir(exist_ok=True)
        GOLDEN.write_text(json.dumps(got, indent=2) + "\n")
        pytest.skip("golden updated")
    assert GOLDEN.exists(), (
        "golden artifact missing; regenerate with KFT_UPDATE_GOLDEN=1")
    want = json.loads(GOLDEN.read_text())
    np.testing.assert_allclose(
        np.asarray(got["scores"]), np.asarray(want["scores"]),
        atol=5e-3,
        err_msg="serving scores drifted from the committed golden",
    )
    # The argmax class must be stable even where scores wiggle in the
    # last decimals (the reference's textual diff pinned exactly this).
    assert got["top_k_classes"][0] == want["top_k_classes"][0]


def test_metadata_signature_stable(served_api):
    meta = served_api.metadata("inception")
    assert meta["metadata"]["signature"]["inputs"] == {
        "image": [None, 96, 96, 3]}
    assert meta["model_spec"]["name"] == "inception"
