"""HttpKube (stdlib REST backend) against a real HTTP API server.

Round-3 verdict, weak #9: the real-cluster kube backend was "trust-me"
— no API server existed to run it against.  Now the stdlib HTTP backend
executes over real localhost sockets against
testing/fake_apiserver.py, which speaks the Kubernetes REST contract
backed by the same FakeKube store the unit tests use.  URL shapes,
label-selector encoding, the merge-patch status content type, and the
404/409 -> NotFound/Conflict mapping are integration facts here, not
code review.
"""

import pytest

from kubeflow_tpu.operator.gang import GangScheduler
from kubeflow_tpu.operator.kube import Conflict, NotFound
from kubeflow_tpu.operator.kube_http import HttpKube
from kubeflow_tpu.operator.reconciler import TPUJobController
from kubeflow_tpu.testing.fake_apiserver import make_fake_apiserver


@pytest.fixture()
def served():
    httpd, thread, store = make_fake_apiserver()
    port = httpd.server_address[1]
    client = HttpKube(base_url=f"http://127.0.0.1:{port}")
    yield client, store
    httpd.shutdown()
    httpd.server_close()  # release the listening socket FD


def _pod(ns, name, labels=None):
    return {"metadata": {"namespace": ns, "name": name,
                         "labels": labels or {}},
            "spec": {"containers": []}}


class TestPods:
    def test_create_get_list_delete(self, served):
        client, store = served
        client.create_pod(_pod("ns1", "p0", {"app": "x"}))
        client.create_pod(_pod("ns1", "p1", {"app": "y"}))
        got = client.get_pod("ns1", "p0")
        assert got["status"]["phase"] == "Pending"
        assert len(client.list_pods("ns1")) == 2
        only_x = client.list_pods("ns1", labels={"app": "x"})
        assert [p["metadata"]["name"] for p in only_x] == ["p0"]
        client.delete_pod("ns1", "p0")
        assert store.deleted_pods == ["ns1/p0"]
        with pytest.raises(NotFound):
            client.get_pod("ns1", "p0")

    def test_conflict_maps_to_conflict(self, served):
        client, _ = served
        client.create_pod(_pod("ns1", "dup"))
        with pytest.raises(Conflict):
            client.create_pod(_pod("ns1", "dup"))

    def test_delete_missing_maps_to_notfound(self, served):
        client, _ = served
        with pytest.raises(NotFound):
            client.delete_pod("ns1", "ghost")

    def test_multi_label_selector(self, served):
        client, _ = served
        client.create_pod(_pod("ns1", "a", {"job": "j", "idx": "0"}))
        client.create_pod(_pod("ns1", "b", {"job": "j", "idx": "1"}))
        client.create_pod(_pod("ns1", "c", {"job": "k", "idx": "0"}))
        out = client.list_pods("ns1", labels={"job": "j", "idx": "1"})
        assert [p["metadata"]["name"] for p in out] == ["b"]


class TestCustomResources:
    def test_crud_and_status_patch(self, served):
        client, store = served
        cr = {"apiVersion": "kubeflow-tpu.org/v1alpha1", "kind": "TPUJob",
              "metadata": {"namespace": "ns1", "name": "job"},
              "spec": {"sliceType": "v5e-16"}}
        client.create_custom(cr)
        assert client.get_custom("ns1", "job")["spec"]["sliceType"] \
            == "v5e-16"
        assert len(client.list_custom("ns1")) == 1
        client.update_custom_status("ns1", "job", {"phase": "Running"})
        assert store.custom[("ns1", "job")]["status"]["phase"] == "Running"
        client.delete_custom("ns1", "job")
        with pytest.raises(NotFound):
            client.get_custom("ns1", "job")
        # Idempotent delete (FakeKube backend semantics preserved).
        client.delete_custom("ns1", "job")

    def test_events_recorded_best_effort(self, served):
        client, store = served
        client.record_event("ns1", "TPUJob/job", "Admitted", "gang ok")
        assert store.events and store.events[0]["reason"] == "Admitted"


class TestTransientRetry:
    """Satellite: transient apiserver 5xx / connection failures are
    retried with capped jittered backoff so one blip does not fail a
    reconcile pass; semantic 4xx are answers, never retried."""

    def _client(self, httpd, **kw):
        kw.setdefault("retries", 3)
        kw.setdefault("retry_backoff_s", 0.002)
        return HttpKube(
            base_url=f"http://127.0.0.1:{httpd.server_address[1]}", **kw)

    @pytest.fixture()
    def raw(self):
        from kubeflow_tpu.testing.fake_apiserver import make_fake_apiserver

        httpd, thread, store = make_fake_apiserver()
        yield httpd, store
        httpd.shutdown()
        httpd.server_close()

    def test_5xx_retried_to_success(self, raw):
        httpd, store = raw
        client = self._client(httpd)
        store.create_pod(_pod("ns1", "p0"))
        httpd.fail_queue.extend([503, 500])
        pods = client.list_pods("ns1")
        assert [p["metadata"]["name"] for p in pods] == ["p0"]
        assert httpd.fail_queue == []  # both injected failures consumed

    def test_retries_exhausted_raises(self, raw):
        httpd, _ = raw
        client = self._client(httpd, retries=2)
        httpd.fail_queue.extend([503, 503, 503])  # one more than budget
        with pytest.raises(RuntimeError, match="-> 503"):
            client.list_pods("ns1")

    def test_semantic_4xx_never_retried(self, raw):
        from kubeflow_tpu.testing import faults

        httpd, _ = raw
        client = self._client(httpd)
        with faults.injected("seed=0") as inj:
            with pytest.raises(NotFound):
                client.get_pod("ns1", "ghost")
            # Exactly one transport attempt: 404 is an answer.
            assert inj.fired("kube.request") == 1

    def test_connection_errors_retried(self, raw):
        """Scripted connection failures (fault harness, fired before
        the socket) are transparently retried like 5xx weather."""
        from kubeflow_tpu.testing import faults

        httpd, store = raw
        client = self._client(httpd)
        store.create_pod(_pod("ns1", "p0"))
        with faults.injected("seed=0;kube.request:raise*2") as inj:
            pods = client.list_pods("ns1")
            assert len(pods) == 1
            assert inj.fired("kube.request") == 3  # 2 failures + success

    def test_mutations_never_retried(self, raw):
        """POST/DELETE fail fast on 5xx: a replay of a mutation whose
        response was lost could double-apply it (duplicate create ->
        spurious Conflict); the reconciler's resweep is their retry."""
        from kubeflow_tpu.testing import faults

        httpd, store = raw
        client = self._client(httpd)
        httpd.fail_queue.append(503)
        with faults.injected("seed=0") as inj:
            with pytest.raises(RuntimeError, match="-> 503"):
                client.create_pod(_pod("ns1", "p0"))
            assert inj.fired("kube.request") == 1  # no replay
        assert httpd.fail_queue == []
        assert store.pods == {}  # nothing half-applied either

    def test_connection_errors_exhausted_raise(self, raw):
        from kubeflow_tpu.testing import faults

        httpd, _ = raw
        client = self._client(httpd, retries=1)
        with faults.injected("kube.request:raise"):
            with pytest.raises(RuntimeError, match="after 2 attempts"):
                client.list_pods("ns1")


class TestDeployments:
    """apps/v1 Deployment slice (the fleet autoscaler's scale target)
    over real sockets."""

    def test_create_get_list_scale(self, served):
        client, store = served
        client.create_deployment({
            "metadata": {"namespace": "ns1", "name": "srv",
                         "labels": {"app": "srv"}},
            "spec": {"replicas": 2}})
        got = client.get_deployment("ns1", "srv")
        assert got["spec"]["replicas"] == 2
        assert len(client.list_deployments("ns1")) == 1
        assert client.list_deployments(
            "ns1", labels={"app": "other"}) == []
        client.patch_deployment_scale("ns1", "srv", 5)
        assert store.deployments[("ns1", "srv")]["spec"]["replicas"] \
            == 5
        # Idempotent re-apply (PATCH semantics): same answer, no error.
        client.patch_deployment_scale("ns1", "srv", 5)
        assert client.get_deployment("ns1", "srv")["spec"]["replicas"] \
            == 5

    def test_scale_of_missing_deployment_is_notfound(self, served):
        client, _ = served
        with pytest.raises(NotFound):
            client.patch_deployment_scale("ns1", "ghost", 3)

    def test_scale_patch_rides_transient_retry(self):
        """PATCH is idempotent, so apiserver weather mid-scale is
        retried — a lost scale-to-N response replays onto N."""
        httpd, thread, store = make_fake_apiserver()
        try:
            client = HttpKube(
                base_url=f"http://127.0.0.1:{httpd.server_address[1]}",
                retries=2, retry_backoff_s=0.002)
            client.create_deployment({
                "metadata": {"namespace": "ns1", "name": "srv"},
                "spec": {"replicas": 1}})
            httpd.fail_queue.append(503)
            client.patch_deployment_scale("ns1", "srv", 4)
            assert store.deployments[
                ("ns1", "srv")]["spec"]["replicas"] == 4
            assert httpd.fail_queue == []
        finally:
            httpd.shutdown()
            httpd.server_close()


class TestRetryAfterHonored:
    """Satellite: a server-supplied Retry-After/backoff hint overrides
    the client's own jittered exponential schedule (capped)."""

    @pytest.fixture()
    def raw(self):
        httpd, thread, store = make_fake_apiserver()
        yield httpd, store
        httpd.shutdown()
        httpd.server_close()

    def _client(self, httpd, **kw):
        kw.setdefault("retries", 3)
        kw.setdefault("retry_backoff_s", 0.002)
        return HttpKube(
            base_url=f"http://127.0.0.1:{httpd.server_address[1]}",
            **kw)

    def _recorded_sleeps(self, monkeypatch):
        import kubeflow_tpu.operator.kube_http as mod

        sleeps = []
        real_time = mod.time

        class _Time:
            @staticmethod
            def sleep(s):
                sleeps.append(s)

            def __getattr__(self, name):
                return getattr(real_time, name)

        monkeypatch.setattr(mod, "time", _Time())
        return sleeps

    def test_retry_after_header_overrides_local_schedule(
            self, raw, monkeypatch):
        httpd, store = raw
        client = self._client(httpd, retry_backoff_s=0.001,
                              retry_backoff_cap_s=10.0)
        sleeps = self._recorded_sleeps(monkeypatch)
        store.create_pod(_pod("ns1", "p0"))
        httpd.fail_queue.append((503, "2.5"))
        pods = client.list_pods("ns1")
        assert [p["metadata"]["name"] for p in pods] == ["p0"]
        # One backoff, driven by the server's 2.5s hint (±10% jitter),
        # not the 1ms local schedule.
        assert len(sleeps) == 1
        assert 2.5 <= sleeps[0] <= 2.75 + 1e-9

    def test_retry_after_hint_is_capped(self, raw, monkeypatch):
        httpd, store = raw
        client = self._client(httpd, retry_backoff_cap_s=0.05)
        sleeps = self._recorded_sleeps(monkeypatch)
        store.create_pod(_pod("ns1", "p0"))
        httpd.fail_queue.append((503, "3600"))
        client.list_pods("ns1")
        # A hostile/confused hint cannot park the reconciler: capped.
        assert len(sleeps) == 1 and sleeps[0] <= 0.055 + 1e-9

    def test_429_is_retried_weather(self, raw):
        httpd, store = raw
        client = self._client(httpd)
        store.create_pod(_pod("ns1", "p0"))
        httpd.fail_queue.append((429, "0.001"))
        pods = client.list_pods("ns1")
        assert len(pods) == 1
        assert httpd.fail_queue == []

    def test_5xx_without_hint_keeps_local_jitter(self, raw,
                                                 monkeypatch):
        httpd, store = raw
        client = self._client(httpd, retry_backoff_s=0.004)
        sleeps = self._recorded_sleeps(monkeypatch)
        store.create_pod(_pod("ns1", "p0"))
        httpd.fail_queue.append(503)
        client.list_pods("ns1")
        # Full-jitter window of the LOCAL schedule: [0.5, 1.0] * base.
        assert len(sleeps) == 1
        assert 0.002 <= sleeps[0] <= 0.004 + 1e-9


class TestReconcileOverHTTP:
    def test_full_job_lifecycle_through_real_sockets(self, served):
        """The SAME controller the in-memory tests drive, now with every
        kube call crossing a localhost HTTP boundary: submit -> admit ->
        gang pods created -> phases flipped -> job Succeeded."""
        client, store = served
        ctl = TPUJobController(client, GangScheduler({"v5e-16": 1}))
        store.create_custom({
            "apiVersion": "kubeflow-tpu.org/v1alpha1", "kind": "TPUJob",
            "metadata": {"namespace": "default", "name": "train"},
            "spec": {"sliceType": "v5e-16",
                     "worker": {"image": "img:1", "args": ["--steps=1"]}},
        })
        ctl.reconcile_all()   # admit + create gang
        pods = client.list_pods(
            "default", labels={"kubeflow-tpu.org/job-name": "train"})
        assert pods, "gang pods were not created over HTTP"
        ctl.reconcile_all()
        for p in pods:
            store.set_pod_phase("default", p["metadata"]["name"],
                                "Running")
        ctl.reconcile_all()
        assert store.custom[("default", "train")]["status"]["phase"] \
            == "Running"
        for p in pods:
            store.set_pod_phase("default", p["metadata"]["name"],
                                "Succeeded")
        ctl.reconcile_all()
        assert store.custom[("default", "train")]["status"]["phase"] \
            == "Succeeded"
