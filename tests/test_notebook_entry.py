"""Notebook single-user entry: PVC-home seeding + arg assembly.

The reference's pvc-check.sh / start-singleuser.sh logic
(/root/reference/components/tensorflow-notebook-image/) re-provided as a
testable module — these tests pin the behavioral contract the shell
scripts enforced in-image only.
"""

from kubeflow_tpu.tools.notebook_entry import (
    build_args,
    home_needs_init,
    init_home,
)


class TestHomeInit:
    def test_empty_home_needs_init(self, tmp_path):
        assert home_needs_init(tmp_path)

    def test_lost_and_found_only_still_fresh(self, tmp_path):
        # A newly-provisioned ext4 PV carries lost+found; that alone
        # must not count as user content (the reference's
        # `ls --ignore=lost+found` check).
        (tmp_path / "lost+found").mkdir()
        assert home_needs_init(tmp_path)

    def test_user_content_blocks_init(self, tmp_path):
        (tmp_path / "thesis.ipynb").write_text("{}")
        assert not home_needs_init(tmp_path)

    def test_init_seeds_work_and_config(self, tmp_path):
        seed = tmp_path / "seed_config.py"
        seed.write_text("c = get_config()\n")
        home = tmp_path / "home"
        home.mkdir()
        created = init_home(home, seed_config=str(seed))
        assert (home / "work").is_dir()
        assert (home / ".jupyter" / "seed_config.py").read_text() \
            == "c = get_config()\n"
        assert str(home / "work") in created

    def test_init_is_noop_on_populated_home(self, tmp_path):
        (tmp_path / "notes.txt").write_text("mine")
        assert init_home(tmp_path) == []
        # Nothing else appeared.
        assert sorted(p.name for p in tmp_path.iterdir()) == ["notes.txt"]

    def test_init_without_seed_config_still_creates_dirs(self, tmp_path):
        created = init_home(tmp_path, seed_config=str(tmp_path / "nope.py"))
        assert (tmp_path / "work").is_dir()
        assert (tmp_path / ".jupyter").is_dir()
        assert len(created) == 2


class TestArgs:
    def test_default_binds_all_interfaces(self):
        assert "--ip=0.0.0.0" in build_args(environ={})

    def test_caller_ip_wins(self):
        args = build_args(environ={}, extra=["--ip=127.0.0.1"])
        assert args.count("--ip=127.0.0.1") == 1
        assert "--ip=0.0.0.0" not in args

    def test_notebook_dir_env_mapped(self):
        args = build_args(environ={"NOTEBOOK_DIR": "/home/jovyan/work"})
        assert "--notebook-dir=/home/jovyan/work" in args

    def test_extra_args_pass_through_after_defaults(self):
        args = build_args(environ={}, extra=["--debug"])
        assert args[0] == "jupyterhub-singleuser"
        assert args[-1] == "--debug"
