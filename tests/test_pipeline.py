"""Pipeline parallelism: GPipe schedule vs sequential reference."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from kubeflow_tpu.parallel import MeshSpec, PIPELINE
from kubeflow_tpu.parallel.pipeline import (
    microbatch,
    pipelined_scan,
    unmicrobatch,
)

L, D = 8, 16  # layers, width


def layer_fn(params, x):
    w, b = params
    return jnp.tanh(x @ w + b)


def make_params(rng, layers=L):
    return (
        jnp.asarray(rng.randn(layers, D, D) * 0.3, jnp.float32),
        jnp.asarray(rng.randn(layers, D) * 0.1, jnp.float32),
    )


def sequential(params, x):
    def body(carry, layer):
        return layer_fn(layer, carry), None

    out, _ = jax.lax.scan(body, x, params)
    return out


@pytest.mark.parametrize("n_stages,n_micro", [(2, 4), (4, 4), (4, 8), (8, 8)])
def test_matches_sequential(devices, n_stages, n_micro):
    mesh = MeshSpec(data=1, pipeline=n_stages).build(devices[:n_stages])
    rng = np.random.RandomState(0)
    params = make_params(rng)
    x = jnp.asarray(rng.randn(32, D), jnp.float32)
    ref = sequential(params, x)

    @jax.jit
    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=((P(PIPELINE), P(PIPELINE)), P()),
        out_specs=P(),
    )
    def piped(params, x):
        xm = microbatch(x, n_micro)
        out = pipelined_scan(layer_fn, params, xm)
        return unmicrobatch(out)

    np.testing.assert_allclose(
        np.asarray(piped(params, x)), np.asarray(ref), atol=1e-5
    )


def test_gradients_flow(devices):
    mesh = MeshSpec(data=1, pipeline=4).build(devices[:4])
    rng = np.random.RandomState(1)
    params = make_params(rng)
    x = jnp.asarray(rng.randn(8, D), jnp.float32)

    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=((P(PIPELINE), P(PIPELINE)), P()),
        out_specs=P(),
    )
    def piped(params, x):
        return unmicrobatch(pipelined_scan(layer_fn, params, microbatch(x, 4)))

    g_pipe = jax.grad(lambda p, v: jax.jit(piped)(p, v).sum())(params, x)
    g_ref = jax.grad(lambda p, v: sequential(p, v).sum())(params, x)
    for a, b in zip(jax.tree_util.tree_leaves(g_pipe),
                    jax.tree_util.tree_leaves(g_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_microbatch_divisibility():
    with pytest.raises(ValueError, match="divisible"):
        microbatch(jnp.zeros((10, 4)), 3)


@pytest.mark.parametrize("n_stages,n_micro", [(2, 4), (4, 4)])
def test_with_aux_matches_sequential(devices, n_stages, n_micro):
    """with_aux (the MoE aux-loss thread): the accumulated aux equals
    the sum over every (layer, microbatch) pair of the per-call aux —
    bubble steps contribute nothing — and gradients flow through it."""
    mesh = MeshSpec(data=1, pipeline=n_stages).build(devices[:n_stages])
    rng = np.random.RandomState(2)
    params = make_params(rng)
    x = jnp.asarray(rng.randn(16, D), jnp.float32)

    def fn(layer, a):
        # aux depends on the INPUT activation, so a bubble step running
        # on stale/zero data would poison the total if unmasked.
        return layer_fn(layer, a), jnp.sum(a * a)

    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=((P(PIPELINE), P(PIPELINE)), P()),
        out_specs=(P(), P()),
    )
    def piped(params, x):
        ys, aux = pipelined_scan(fn, params, microbatch(x, n_micro),
                                 with_aux=True)
        return unmicrobatch(ys), aux

    def ref(params, x):
        total = jnp.zeros(())
        ys = []
        for m in range(n_micro):
            act = microbatch(x, n_micro)[m]
            for layer in range(L):
                total = total + jnp.sum(act * act)
                act = layer_fn(
                    jax.tree_util.tree_map(lambda p: p[layer], params),
                    act)
            ys.append(act)
        return jnp.concatenate(ys, axis=0), total

    out, aux = jax.jit(piped)(params, x)
    ref_out, ref_aux = ref(params, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref_out),
                               atol=1e-5)
    np.testing.assert_allclose(float(aux), float(ref_aux), rtol=1e-6)

    g = jax.grad(lambda p, v: jax.jit(piped)(p, v)[1])(params, x)
    g_ref = jax.grad(lambda p, v: ref(p, v)[1])(params, x)
    for a, b in zip(jax.tree_util.tree_leaves(g),
                    jax.tree_util.tree_leaves(g_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-5)
