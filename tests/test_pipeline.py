"""Pipeline parallelism: GPipe schedule vs sequential reference."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from kubeflow_tpu.parallel import MeshSpec, PIPELINE
from kubeflow_tpu.parallel.pipeline import (
    microbatch,
    pipelined_scan,
    unmicrobatch,
)

L, D = 8, 16  # layers, width


def layer_fn(params, x):
    w, b = params
    return jnp.tanh(x @ w + b)


def make_params(rng, layers=L):
    return (
        jnp.asarray(rng.randn(layers, D, D) * 0.3, jnp.float32),
        jnp.asarray(rng.randn(layers, D) * 0.1, jnp.float32),
    )


def sequential(params, x):
    def body(carry, layer):
        return layer_fn(layer, carry), None

    out, _ = jax.lax.scan(body, x, params)
    return out


@pytest.mark.parametrize("n_stages,n_micro", [(2, 4), (4, 4), (4, 8), (8, 8)])
def test_matches_sequential(devices, n_stages, n_micro):
    mesh = MeshSpec(data=1, pipeline=n_stages).build(devices[:n_stages])
    rng = np.random.RandomState(0)
    params = make_params(rng)
    x = jnp.asarray(rng.randn(32, D), jnp.float32)
    ref = sequential(params, x)

    @jax.jit
    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=((P(PIPELINE), P(PIPELINE)), P()),
        out_specs=P(),
    )
    def piped(params, x):
        xm = microbatch(x, n_micro)
        out = pipelined_scan(layer_fn, params, xm)
        return unmicrobatch(out)

    np.testing.assert_allclose(
        np.asarray(piped(params, x)), np.asarray(ref), atol=1e-5
    )


def test_gradients_flow(devices):
    mesh = MeshSpec(data=1, pipeline=4).build(devices[:4])
    rng = np.random.RandomState(1)
    params = make_params(rng)
    x = jnp.asarray(rng.randn(8, D), jnp.float32)

    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=((P(PIPELINE), P(PIPELINE)), P()),
        out_specs=P(),
    )
    def piped(params, x):
        return unmicrobatch(pipelined_scan(layer_fn, params, microbatch(x, 4)))

    g_pipe = jax.grad(lambda p, v: jax.jit(piped)(p, v).sum())(params, x)
    g_ref = jax.grad(lambda p, v: sequential(p, v).sum())(params, x)
    for a, b in zip(jax.tree_util.tree_leaves(g_pipe),
                    jax.tree_util.tree_leaves(g_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_microbatch_divisibility():
    with pytest.raises(ValueError, match="divisible"):
        microbatch(jnp.zeros((10, 4)), 3)
