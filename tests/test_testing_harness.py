"""Tests for the CI harness: JUnit emission, workflow DAG, e2e drivers."""

import xml.etree.ElementTree as ET

import pytest

from kubeflow_tpu.testing.e2e import serving_smoke, tpujob_smoke
from kubeflow_tpu.testing.junit import JUnitSuite
from kubeflow_tpu.testing.workflow import Step, default_e2e


class TestJUnit:
    def test_pass_fail_error_classification(self, tmp_path):
        suite = JUnitSuite("demo")
        suite.run("ok", lambda: None)
        suite.run("fails", lambda: (_ for _ in ()).throw(AssertionError("x")))
        suite.run("errors", lambda: (_ for _ in ()).throw(RuntimeError("y")))
        path = suite.write(tmp_path)
        root = ET.parse(path).getroot()
        assert root.get("tests") == "3"
        assert root.get("failures") == "1"
        assert root.get("errors") == "1"
        assert not suite.ok

    def test_xml_escaping(self, tmp_path):
        suite = JUnitSuite("esc")
        suite.run("bad<name>", lambda: None)
        root = ET.parse(suite.write(tmp_path)).getroot()
        assert root[0].get("name") == "bad<name>"


class TestWorkflowDAG:
    def test_default_dag_shape(self):
        cr = default_e2e(artifacts_gcs="gs://bucket/artifacts")
        assert cr.to_custom_resource()["kind"] == "Workflow"
        spec = cr.to_custom_resource()["spec"]
        dag = [t for t in spec["templates"] if t["name"] == "main"][0]["dag"]
        by_name = {t["name"]: t for t in dag["tasks"]}
        assert by_name["deploy-kubeflow"]["dependencies"] == ["checkout"]
        assert by_name["tpujob-test"]["dependencies"] == ["deploy-kubeflow"]
        assert spec["onExit"] == "exit-handler"
        exit_tmpl = [t for t in spec["templates"]
                     if t["name"] == "exit-handler"][0]
        names = [s[0]["name"] for s in exit_tmpl["steps"]]
        assert names == ["teardown", "copy-artifacts"]

    def test_custom_step_env(self):
        wf = default_e2e().add_step(
            Step("extra", ["true"], env={"A": "1"}, deps=["checkout"]))
        cr = wf.to_custom_resource()
        tmpl = [t for t in cr["spec"]["templates"] if t["name"] == "extra"][0]
        assert tmpl["container"]["env"] == [{"name": "A", "value": "1"}]


class TestE2EDrivers:
    def test_tpujob_smoke(self):
        tpujob_smoke()

    def test_serving_smoke(self):
        serving_smoke()
