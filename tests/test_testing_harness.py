"""Tests for the CI harness: JUnit emission, workflow DAG, e2e drivers."""

import xml.etree.ElementTree as ET

import pytest

from kubeflow_tpu.testing.e2e import (
    adapter_serving_smoke,
    colocation_smoke,
    engine_smoke,
    fault_injection_smoke,
    fleet_smoke,
    hfta_smoke,
    kv_spill_smoke,
    multichip_serving_smoke,
    scheduler_smoke,
    serving_smoke,
    survivable_smoke,
    tpujob_smoke,
    train_resilience_smoke,
)
from kubeflow_tpu.testing.junit import JUnitSuite
from kubeflow_tpu.testing.workflow import Step, default_e2e


class TestJUnit:
    def test_pass_fail_error_classification(self, tmp_path):
        suite = JUnitSuite("demo")
        suite.run("ok", lambda: None)
        suite.run("fails", lambda: (_ for _ in ()).throw(AssertionError("x")))
        suite.run("errors", lambda: (_ for _ in ()).throw(RuntimeError("y")))
        path = suite.write(tmp_path)
        root = ET.parse(path).getroot()
        assert root.get("tests") == "3"
        assert root.get("failures") == "1"
        assert root.get("errors") == "1"
        assert not suite.ok

    def test_xml_escaping(self, tmp_path):
        suite = JUnitSuite("esc")
        suite.run("bad<name>", lambda: None)
        root = ET.parse(suite.write(tmp_path)).getroot()
        assert root[0].get("name") == "bad<name>"


class TestWorkflowDAG:
    def test_default_dag_shape(self):
        cr = default_e2e(artifacts_gcs="gs://bucket/artifacts")
        assert cr.to_custom_resource()["kind"] == "Workflow"
        spec = cr.to_custom_resource()["spec"]
        dag = [t for t in spec["templates"] if t["name"] == "main"][0]["dag"]
        by_name = {t["name"]: t for t in dag["tasks"]}
        assert by_name["deploy-kubeflow"]["dependencies"] == ["checkout"]
        assert by_name["tpujob-test"]["dependencies"] == ["deploy-kubeflow"]
        assert spec["onExit"] == "exit-handler"
        exit_tmpl = [t for t in spec["templates"]
                     if t["name"] == "exit-handler"][0]
        names = [s[0]["name"] for s in exit_tmpl["steps"]]
        assert names == ["teardown", "copy-artifacts"]

    def test_custom_step_env(self):
        wf = default_e2e().add_step(
            Step("extra", ["true"], env={"A": "1"}, deps=["checkout"]))
        cr = wf.to_custom_resource()
        tmpl = [t for t in cr["spec"]["templates"] if t["name"] == "extra"][0]
        assert tmpl["container"]["env"] == [{"name": "A", "value": "1"}]


class TestE2EDrivers:
    def test_tpujob_smoke(self):
        tpujob_smoke()

    def test_scheduler_smoke(self):
        # The ci/e2e_config.yaml hermetic `scheduler` step: two
        # tenants over the fake apiserver — quota-capped greedy
        # tenant, backfill past a blocked large job, priority
        # preemption through the checkpoint grace window with a
        # resumed-from-latest-step victim, kft_scheduler_* metrics
        # (see kubeflow_tpu/testing/e2e.py scheduler_smoke).
        scheduler_smoke()

    def test_serving_smoke(self):
        serving_smoke()

    def test_engine_smoke(self):
        # The ci/e2e_config.yaml hermetic `engine` step: mixed-length
        # requests through the HTTP surface against the in-process
        # continuous-batching engine (occupancy drains to zero), a
        # shared-prefix burst (kft_engine_prefix_hits_total > 0,
        # bounded inter-token gap), and a speculative burst
        # (kft_engine_spec_accepted_total > 0, four compiled
        # programs, token-identical to a spec-OFF control).
        engine_smoke()

    def test_fault_injection_smoke(self):
        # The ci/e2e_config.yaml hermetic `faults` step: the seeded
        # KFT_FAULTS chaos scenario — overload shed (429+Retry-After),
        # mid-generation deadline expiry (504) with slot reuse, loader
        # circuit-break with last-good serving, graceful drain, and
        # kft_* metric visibility of every outcome.
        fault_injection_smoke()

    def test_fleet_smoke(self):
        # The ci/e2e_config.yaml hermetic `fleet` step: router + 3
        # in-process replicas + fake apiserver — scale-out under
        # open-loop load, replica kill -> ejection -> recovery, and a
        # drain-aware rolling restart with zero lost accepted
        # requests (see kubeflow_tpu/testing/e2e.py fleet_smoke).
        fleet_smoke()

    def test_survivable_smoke(self):
        # The ci/e2e_config.yaml hermetic `survivable` step: router +
        # 3 engine replicas under a seeded kill-mid-generation
        # schedule — every accepted greedy :generate stream completes
        # bit-identical to an uninterrupted control (resume-based
        # failover + stream splicing), the dead replica force-ejects
        # and readmits after restart, a double-submitted :predict with
        # one idempotency key executes once, and
        # kft_router_replays_total{outcome="ok"} /
        # kft_serving_dedup_hits_total move as /metrics deltas (see
        # kubeflow_tpu/testing/e2e.py survivable_smoke).
        survivable_smoke()

    def test_kv_spill_smoke(self):
        # The ci/e2e_config.yaml hermetic `kv_spill` step: router + 3
        # engine replicas with a TIGHT 12-page device pool and a host
        # spill tier (user_guide §5.10) — parked multi-turn sessions
        # overflow to host RAM with zero sheds and zero destructive
        # evictions, a resumed session re-imports its spilled pages
        # bit-identical to an uninterrupted control, and a
        # kill-mid-generation failover resumes by FETCHING the
        # session's pages from a surviving peer
        # (kft_router_kv_fetch_total{outcome="ok"} delta; see
        # kubeflow_tpu/testing/e2e.py kv_spill_smoke).
        kv_spill_smoke()

    def test_multichip_serving_smoke(self):
        # The ci/e2e_config.yaml hermetic `multichip_serving` step:
        # prefill + decode tiers behind the router over the forced
        # multi-device host platform (the conftest's 8 fake chips) —
        # tiered :generate streams identical to a unified control,
        # block-page handoff counters moving as /metrics deltas, the
        # decode replica's engine tensor-parallel over a 2-device
        # mesh, and decode-pool death shedding typed 429 (see
        # kubeflow_tpu/testing/e2e.py multichip_serving_smoke).
        multichip_serving_smoke()

    def test_adapter_serving_smoke(self):
        # The ci/e2e_config.yaml hermetic `adapter_serving` step:
        # three per-tenant adapters over a 2-replica engine fleet
        # behind the router (user_guide §5.11) — hot-load under live
        # base traffic, a co-batched mixed burst token-identical to a
        # sequential per-adapter control with the engines reporting
        # only the base program set, evict-under-pressure sparing the
        # pinned in-flight adapter with zero lost accepted requests,
        # /readyz digest advertisement driving router affinity
        # (kft_router_adapter_affinity_total{outcome="hit"} delta),
        # and unknown-adapter typed 404 (see
        # kubeflow_tpu/testing/e2e.py adapter_serving_smoke).
        adapter_serving_smoke()

    def test_train_resilience_smoke(self):
        # The ci/e2e_config.yaml hermetic `train_resilience` step:
        # supervised in-process resume from a VERIFIED checkpoint
        # after an injected train.step fault (params identical to an
        # uninterrupted control run), corrupt-latest walk-back
        # restore, and node-flap -> quarantine + anti-affinity gang
        # re-place over the fake apiserver, with kft_train_* /
        # kft_checkpoint_* metric deltas asserted (see
        # kubeflow_tpu/testing/e2e.py train_resilience_smoke).
        train_resilience_smoke()

    def test_hfta_smoke(self):
        # The ci/e2e_config.yaml hermetic `hfta` step: two tenants'
        # four fusable singleton TPUJobs fold into ONE fused gang
        # (fair-share chip billing inside a quota no singleton could
        # enter), survive a high-priority preemption with every
        # member requeued resumable and resumed, complete per member
        # on pod-gang success; plus the runtime side — a width-4
        # FusedTrainer with one early-stopped masked member killed
        # mid-run resumes from per-member verified checkpoints with
        # steps monotone and params bit-identical to an uninterrupted
        # control (see kubeflow_tpu/testing/e2e.py hfta_smoke).
        hfta_smoke()

    def test_colocation_smoke(self):
        # The ci/e2e_config.yaml hermetic `colocation` step: the real
        # fleet Autoscaler in claims mode over the fake apiserver —
        # a scripted diurnal burst writes a serving claim that evicts
        # low-priority training on the SHORT serving grace (prepull
        # pods pinned to the victim's nodes), the reconciler patches
        # the Deployment only on grant, and the evening trough's
        # released chips backfill the victim, which resumes
        # bit-identical from its verified checkpoint (see
        # kubeflow_tpu/testing/e2e.py colocation_smoke).
        colocation_smoke()


class _FakeKubectl:
    """Records kubectl invocations; scripted stdout per verb."""

    def __init__(self):
        self.calls = []
        self.job_phase = "Succeeded"

    def __call__(self, cmd, input=None, text=None, capture_output=None,
                 timeout=None):
        import types

        assert cmd[0] == "kubectl"
        self.calls.append((cmd[1:], input))
        stdout = ""
        if cmd[1] == "get" and "-o" in cmd:
            stdout = ('{"status": {"phase": "%s"}}' % self.job_phase)
        return types.SimpleNamespace(returncode=0, stdout=stdout,
                                     stderr="")


class TestRealClusterDrivers:
    """The deploy-then-verify path (heir of
    testing/test_deploy.py:160-190) against a scripted kubectl — the
    real code path short of a live apiserver; ci/run_e2e_kind.sh runs
    the same commands against an actual kind cluster."""

    def test_deploy_applies_and_waits(self, monkeypatch):
        import subprocess

        from kubeflow_tpu.testing import e2e

        fake = _FakeKubectl()
        monkeypatch.setattr(subprocess, "run", fake)
        e2e.deploy_real("kf-e2e")
        verbs = [c[0][0] for c in fake.calls]
        assert "apply" in verbs
        applied = [c for c in fake.calls if c[0][0] == "apply"][0]
        assert "kind: Deployment" in applied[1]
        # Every rendered Deployment gets a rollout wait (readiness
        # budget, test_deploy.py:188-189).
        rollouts = [c[0] for c in fake.calls if c[0][0] == "rollout"]
        assert len(rollouts) >= 3
        assert all("--timeout=600s" in r for r in rollouts)

    def test_tpujob_real_polls_to_success(self, monkeypatch):
        import subprocess

        from kubeflow_tpu.testing import e2e

        fake = _FakeKubectl()
        monkeypatch.setattr(subprocess, "run", fake)
        e2e.tpujob_real("kf-e2e")
        applied = [c for c in fake.calls if c[0][0] == "apply"][0]
        assert "TPUJob" in applied[1]
        assert any(c[0][0] == "get" for c in fake.calls)

    def test_tpujob_real_fails_on_failed_phase(self, monkeypatch):
        import subprocess

        import pytest

        from kubeflow_tpu.testing import e2e

        fake = _FakeKubectl()
        fake.job_phase = "Failed"
        monkeypatch.setattr(subprocess, "run", fake)
        with pytest.raises(AssertionError, match="Failed"):
            e2e.tpujob_real("kf-e2e")
