"""Executed torch worker profile (heir of the reference's pytorch-job
path, kubeflow/pytorch-job/pytorch-operator.libsonnet:30-80): the
torch-xla-job manifest is not write-only — its env contract drives a
real torch training process."""

import os
import subprocess
import sys

import pytest

torch = pytest.importorskip("torch")

from kubeflow_tpu.runtime.bootstrap import WorkerEnv
from kubeflow_tpu.tools.train_torch import main, torch_dist_env


class TestDistEnvContract:
    def test_kft_to_torch_env(self):
        env = WorkerEnv(coordinator_address="job-worker-0.job.ns:12355",
                        num_processes=4, process_id=2, job_name="job")
        out = torch_dist_env(env)
        assert out == {
            "RANK": "2", "WORLD_SIZE": "4",
            "MASTER_ADDR": "job-worker-0.job.ns",
            "MASTER_PORT": "12355",
        }

    def test_single_process_defaults(self):
        env = WorkerEnv(coordinator_address=None, num_processes=1,
                        process_id=0)
        out = torch_dist_env(env)
        assert out["MASTER_ADDR"] == "127.0.0.1"
        assert out["WORLD_SIZE"] == "1"


class TestExecutedWorker:
    def test_single_process_trains(self):
        assert main(["--steps", "3", "--batch-size", "4",
                     "--features", "2"]) == 0

    @pytest.mark.slow  # ~21s two-process gloo spin-up; single-process stays tier-1
    def test_two_process_gloo_gang(self, tmp_path):
        """Two real processes rendezvous over the KFT contract and take
        DDP-averaged steps — the executed equivalent of the reference's
        dist_mnist two-replica check (BASELINE.json config 3)."""
        procs = []
        for rank in range(2):
            env = dict(
                os.environ,
                KFT_COORDINATOR_ADDRESS="127.0.0.1:29511",
                KFT_NUM_PROCESSES="2",
                KFT_PROCESS_ID=str(rank),
                KFT_JOB_NAME="torch-smoke",
            )
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "kubeflow_tpu.tools.train_torch",
                 "--steps", "2", "--batch-size", "4", "--features", "2"],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            ))
        for p in procs:
            _, err = p.communicate(timeout=300)
            assert p.returncode == 0, err.decode()[-2000:]
