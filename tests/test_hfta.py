"""Horizontally fused training arrays (runtime/hfta.py).

The HFTA contract is BIT-identity, not allclose: member i of a fused
run must produce exactly the arrays its width-1 solo run produces —
across fused widths, across an early-stopped peer, and across a
preempt/resume boundary.  The solo control is therefore a WIDTH-1
FusedTrainer run (the same vmapped step): a plain ``Trainer`` step
differs from the batched-GEMM accumulation order at ~1e-8 and is only
allclose-comparable.

Same-task FusedTrainers share one compiled step (the process-level
cache in runtime/hfta.py), so only the first run of each WIDTH pays a
trace; the width-4 reference run is still a module fixture so its 5
stepped batches are shared by the invariance, early-stop and resume
tests — the suite stays inside the tier-1 time budget.
"""

import numpy as np
import jax
import pytest

from kubeflow_tpu.models.transformer import TransformerConfig, lm_task
from kubeflow_tpu.parallel import MeshSpec
from kubeflow_tpu.runtime.checkpoint import CheckpointManager
from kubeflow_tpu.runtime.hfta import FusedTrainer, MemberSpec
from kubeflow_tpu.runtime.metrics import MetricsLogger

VOCAB, SEQ, BATCH = 64, 16, 8


def data_factory():
    r = np.random.RandomState(0)
    while True:
        yield {"tokens": r.randint(0, VOCAB, size=(BATCH, SEQ))
               .astype(np.int32)}


@pytest.fixture(scope="module")
def task(devices):
    cfg = TransformerConfig(
        vocab_size=VOCAB, d_model=16, n_layers=1, n_heads=2,
        n_kv_heads=2, d_ff=32, head_dim=8, max_seq_len=SEQ,
        dtype="float32")
    mesh = MeshSpec(data=-1).build(devices)
    init_fn, loss_fn = lm_task(cfg, mesh=mesh)
    return init_fn, loss_fn, mesh


def make(task, members, ckpt=None, every=1000):
    init_fn, loss_fn, mesh = task
    return FusedTrainer(
        init_fn=init_fn, loss_fn=loss_fn, members=members, mesh=mesh,
        checkpoint_dir=ckpt, checkpoint_every=every,
        metrics=MetricsLogger(stream=open("/dev/null", "w")))


def specs(n=4, stop=None):
    return [MemberSpec(name=f"m{i}", seed=i, lr=1e-3 * (i + 1),
                       tenant=f"t{i % 2}",
                       stop_step=(stop if i == 1 else None))
            for i in range(n)]


@pytest.fixture(scope="module")
def fused4(task):
    """The width-4 reference: specs(4) for 5 steps, no stops."""
    ft = make(task, specs(4))
    return ft, ft.fit(data_factory(), 5, log_every=10)


def member_leaves(trainer, fused_state, i):
    return [np.asarray(x) for x in jax.tree_util.tree_leaves(
        trainer.member_state(fused_state, i).params)]


def assert_bit_identical(a, b):
    assert len(a) == len(b)
    for x, y in zip(a, b):
        assert np.array_equal(x, y)


class TestWidthInvariance:
    def test_member_params_bit_identical_to_solo_control(self, task,
                                                         fused4):
        """Fused width-4 == width-1 per member: fusion must be
        invisible to each member's trajectory.  Members 0 and 3
        bracket the lr/seed spread; 1 and 2 ride the same vmap lane
        mechanics."""
        ft4, s4 = fused4
        members = specs(4)
        for i in (0, 3):
            ft1 = make(task, [members[i]])
            s1 = ft1.fit(data_factory(), 5, log_every=10)
            assert_bit_identical(member_leaves(ft1, s1, 0),
                                 member_leaves(ft4, s4, i))

    def test_member_validation(self, task):
        with pytest.raises(ValueError, match="duplicate"):
            make(task, [MemberSpec(name="a"), MemberSpec(name="a")])
        with pytest.raises(ValueError, match="at least one"):
            make(task, [])


class TestEarlyStopMasking:
    def test_stopped_member_freezes_peers_unaffected(self, task,
                                                     fused4):
        """m1 early-stops at step 2: its params freeze at the solo
        stop-step state while every peer matches the no-stop run."""
        ft = make(task, specs(4, stop=2))
        s = ft.fit(data_factory(), 5, log_every=10)
        # Everyone is inactive at the end (completing num_steps also
        # deactivates); the early stop shows in the step counters.
        assert ft.last_active == [False, False, False, False]
        steps = [int(ft.member_state(s, i).step) for i in range(4)]
        assert steps == [5, 2, 5, 5]
        # m1 == its own width-1 control run exactly stop_step steps.
        ft1 = make(task, [specs(4)[1]])
        s1 = ft1.fit(data_factory(), 2, log_every=10)
        assert_bit_identical(member_leaves(ft1, s1, 0),
                             member_leaves(ft, s, 1))
        # Peers == the reference run with no stop anywhere.
        ft_full, s_full = fused4
        for i in (0, 2, 3):
            assert_bit_identical(member_leaves(ft_full, s_full, i),
                                 member_leaves(ft, s, i))


class TestResume:
    def test_resume_bit_identical_to_uninterrupted(self, task, fused4,
                                                   tmp_path):
        """Kill after 3 steps, restore_or_init every member, run to
        5: params must be bit-identical to the uninterrupted
        reference run."""
        straight, s_straight = fused4
        ckpt = str(tmp_path / "fused")
        first = make(task, specs(4), ckpt=ckpt)
        first.fit(data_factory(), 3, log_every=10)
        resumed = make(task, specs(4), ckpt=ckpt)
        s_resumed = resumed.fit(data_factory(), 5, log_every=10)
        for i in range(4):
            assert_bit_identical(
                member_leaves(straight, s_straight, i),
                member_leaves(resumed, s_resumed, i))

    def test_member_checkpoints_solo_compatible_and_metered(
            self, task, tmp_path):
        """Each member's checkpoint is an ordinary verified-manifest
        solo checkpoint (a plain CheckpointManager restores it), and
        the run exports per-member step counters + the active gauge."""
        from kubeflow_tpu.runtime.prom import (REGISTRY, parse_metrics,
                                               sample_value)
        ckpt = str(tmp_path / "fused")
        members = specs(2)
        ft = make(task, members, ckpt=ckpt)
        s = ft.fit(data_factory(), 3, log_every=10)
        for i, spec in enumerate(members):
            mgr = CheckpointManager(f"{ckpt}/{spec.name}")
            template = ft.create_member_state(spec)
            restored, start = mgr.restore_or_init(template)
            assert start == 3
            assert_bit_identical(
                [np.asarray(x) for x in
                 jax.tree_util.tree_leaves(restored.params)],
                member_leaves(ft, s, i))
        parsed = parse_metrics(REGISTRY.render())
        for name in ("m0", "m1"):
            assert sample_value(parsed, "kft_train_member_steps_total",
                                member=name) >= 3
        # Both members completed num_steps, so both deactivated.
        assert sample_value(parsed,
                            "kft_train_members_active") == 0.0
