"""Serving plane tests: export/load, version hot-swap, REST contract,
micro-batching.  The REST wire format is checked against the reference
proxy's shapes (instances/predictions, b64, metadata, classify)."""

import base64
import json
import threading
import urllib.request

import jax
import numpy as np
import pytest

from kubeflow_tpu.models.resnet import ResNet18
from kubeflow_tpu.serving.export import export, list_versions, load_version
from kubeflow_tpu.serving.http import (
    ServingAPI,
    decode_b64_if_needed,
    make_http_server,
)
from kubeflow_tpu.serving.model_server import MicroBatcher, ModelServer

CLASSES, IMG = 4, 32


@pytest.fixture(scope="module")
def exported(tmp_path_factory):
    base = tmp_path_factory.mktemp("models") / "tiny"
    model = ResNet18(num_classes=CLASSES, num_filters=8)
    variables = model.init(
        jax.random.key(0), np.zeros((1, IMG, IMG, 3), np.float32),
        train=False,
    )
    export(
        base, 1, variables,
        loader="kubeflow_tpu.serving.loaders:classifier",
        config={"family": "resnet18", "num_classes": CLASSES, "top_k": 2,
                "num_filters": 8},
        signature={"inputs": ["image"],
                   "outputs": ["scores", "top_k_scores", "top_k_classes"]},
    )
    return base, model, variables


# The classifier loader must honor num_filters for the tiny test net.
@pytest.fixture(autouse=True, scope="module")
def _tiny_loader_support():
    yield


class TestExport:
    def test_versions_listed(self, exported):
        base, _, _ = exported
        assert list_versions(base) == [1]

    def test_load_and_predict_matches_direct(self, exported):
        base, model, variables = exported
        predict, meta = load_version(base, 1)
        rng = np.random.RandomState(0)
        img = rng.randn(2, IMG, IMG, 3).astype(np.float32)
        out = predict({"image": img})
        direct = model.apply(variables, img, train=False)
        probs = np.asarray(jax.nn.softmax(direct, axis=-1))
        np.testing.assert_allclose(
            np.asarray(out["scores"]), probs, atol=1e-5
        )
        assert meta["version"] == 1

    def test_duplicate_version_rejected(self, exported):
        base, _, variables = exported
        with pytest.raises(FileExistsError):
            export(base, 1, variables, loader="x:y")


class TestModelServer:
    def test_serves_latest_and_hot_swaps(self, exported, tmp_path):
        src, model, variables = exported
        import shutil

        base = tmp_path / "tiny"
        shutil.copytree(src, base)
        srv = ModelServer()
        srv.add_model("tiny", str(base))
        assert srv.get("tiny").version == 1

        export(
            base, 2, variables,
            loader="kubeflow_tpu.serving.loaders:classifier",
            config={"family": "resnet18", "num_classes": CLASSES,
                    "top_k": 2, "num_filters": 8},
        )
        changed = srv.reload("tiny")
        assert changed and srv.get("tiny").version == 2
        # Old version unloaded (latest-only policy).
        with pytest.raises(KeyError):
            srv.get("tiny", version=1)

    def test_unknown_model(self):
        srv = ModelServer()
        with pytest.raises(KeyError):
            srv.get("nope")


class TestRESTContract:
    @pytest.fixture(scope="class")
    def api(self, exported):
        base, _, _ = exported
        srv = ModelServer()
        srv.add_model("tiny", str(base))
        return ServingAPI(srv)

    def test_predict_instances_to_predictions(self, api):
        rng = np.random.RandomState(1)
        instances = [
            {"image": rng.randn(IMG, IMG, 3).astype(np.float32).tolist()}
            for _ in range(3)
        ]
        out = api.predict("tiny", {"instances": instances})
        assert len(out["predictions"]) == 3
        row = out["predictions"][0]
        assert set(row) == {"scores", "top_k_scores", "top_k_classes"}
        assert len(row["scores"]) == CLASSES

    def test_predict_missing_instances_is_400(self, api):
        with pytest.raises(ValueError, match="instances"):
            api.predict("tiny", {"inputs": []})

    def test_classify_shape(self, api):
        rng = np.random.RandomState(2)
        instances = [
            {"image": rng.randn(IMG, IMG, 3).astype(np.float32).tolist()}
        ]
        out = api.classify("tiny", {"instances": instances})
        pairs = out["result"]["classifications"][0]
        assert len(pairs) == 2  # top_k
        assert isinstance(pairs[0][0], str) and isinstance(pairs[0][1], float)

    def test_metadata(self, api):
        meta = api.metadata("tiny")
        assert meta["model_spec"]["name"] == "tiny"
        assert meta["metadata"]["signature"]["inputs"] == ["image"]

    def test_b64_decode(self):
        raw = np.arange(4, dtype=np.uint8).tobytes()
        decoded = decode_b64_if_needed(
            [{"b64": base64.b64encode(raw).decode()}]
        )
        np.testing.assert_array_equal(decoded[0], np.arange(4, dtype=np.uint8))


class TestWireDtypes:
    """uint8 is shipped to the device as-is (4x fewer wire bytes) and
    scaled to [0,1] on device; integer JSON pixels narrow to uint8."""

    def test_uint8_matches_scaled_float(self, exported):
        base, _, _ = exported
        from kubeflow_tpu.serving.export import load_version

        predict, _ = load_version(base, 1)
        rng = np.random.RandomState(7)
        img_u8 = rng.randint(0, 256, (2, IMG, IMG, 3)).astype(np.uint8)
        out_u8 = predict({"image": img_u8})
        out_f32 = predict(
            {"image": img_u8.astype(np.float32) / 255.0})
        np.testing.assert_allclose(
            np.asarray(out_u8["scores"]), np.asarray(out_f32["scores"]),
            atol=1e-5,
        )

    def test_json_int_pixels_narrow_to_uint8_path(self, exported):
        base, _, _ = exported
        from kubeflow_tpu.serving.export import load_version

        predict, _ = load_version(base, 1)
        rng = np.random.RandomState(8)
        img = rng.randint(0, 256, (1, IMG, IMG, 3))  # int64, JSON-style
        out_int = predict({"image": img})
        out_u8 = predict({"image": img.astype(np.uint8)})
        np.testing.assert_allclose(
            np.asarray(out_int["scores"]), np.asarray(out_u8["scores"]),
            atol=1e-6,
        )

    def test_out_of_range_ints_fall_back_to_float(self, exported):
        base, _, _ = exported
        from kubeflow_tpu.serving.export import load_version

        predict, _ = load_version(base, 1)
        img = np.full((1, IMG, IMG, 3), 1000, dtype=np.int64)
        out = predict({"image": img})  # must not wrap/clip silently
        assert np.asarray(out["scores"]).shape == (1, CLASSES)


class TestHTTPEndToEnd:
    def test_full_http_roundtrip(self, exported):
        base, _, _ = exported
        srv = ModelServer()
        srv.add_model("tiny", str(base))
        httpd, thread = make_http_server(srv, port=0, host="127.0.0.1")
        port = httpd.server_address[1]
        try:
            rng = np.random.RandomState(3)
            body = json.dumps({
                "instances": [
                    {"image": rng.randn(IMG, IMG, 3).astype(
                        np.float32).tolist()}
                ]
            }).encode()
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/model/tiny:predict",
                data=body, headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=30) as resp:
                out = json.loads(resp.read())
            assert len(out["predictions"]) == 1

            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/model/tiny:metadata", timeout=30
            ) as resp:
                meta = json.loads(resp.read())
            assert meta["model_spec"]["version"] == "1"

            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=30
            ) as resp:
                health = json.loads(resp.read())
            assert health["models"] == {"tiny": [1]}

            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=30
            ) as resp:
                metrics = resp.read().decode()
            assert ('kft_serving_requests_total{model="tiny",'
                    'outcome="ok",route="predict"}') in metrics
            assert "kft_serving_request_seconds_bucket" in metrics
        finally:
            httpd.shutdown()


class TestMicroBatcher:
    def test_batches_concurrent_requests(self):
        calls = []

        def predict(inputs):
            calls.append(inputs["x"].shape[0])
            return {"y": inputs["x"] * 2}

        mb = MicroBatcher(predict, max_batch_size=4, batch_timeout_s=0.05,
                          allowed_batch_sizes=[1, 2, 4])
        results = {}

        def worker(i):
            results[i] = mb.submit({"x": np.full((1, 2), float(i))})

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        mb.close()
        for i in range(4):
            np.testing.assert_allclose(
                results[i]["y"], np.full((1, 2), 2.0 * i)
            )
        # Requests were coalesced: fewer device calls than requests.
        assert sum(calls) >= 4 and len(calls) < 4

    def test_cycle_profile_consistent_under_concurrent_runners(self):
        """ADVICE r5 regression: stage timings are accumulated per
        _process locally and folded into self._cycle under the lock —
        with in_flight>1 runners racing a stats() reader, the profile
        must stay internally consistent (every stage present, finite,
        non-negative) instead of showing torn/lost updates."""
        import concurrent.futures as cf

        def predict(inputs):
            return {"y": inputs["x"]}

        mb = MicroBatcher(predict, max_batch_size=4,
                          allowed_batch_sizes=[1, 2, 4],
                          batch_timeout_s=0.002, in_flight=4)
        try:
            snapshots = []
            with cf.ThreadPoolExecutor(9) as ex:
                futures = [
                    ex.submit(mb.submit, {"x": np.full((1, 2), float(i))})
                    for i in range(64)]
                # stats() races the runner threads mid-dispatch.
                for _ in range(16):
                    snapshots.append(mb.stats())
                for f in futures:
                    f.result()
            stats = mb.stats()
        finally:
            mb.close()
        assert stats["requests"] == 64
        assert stats["batches"] == sum(stats["batch_size_hist"].values())
        profile = stats["cycle_profile_ms"]
        assert set(profile) == {"queue_wait", "collate", "pad",
                                "predict", "to_host", "deliver"}
        for stage, ms in profile.items():
            assert np.isfinite(ms) and ms >= 0.0, (stage, ms)
        for snap in snapshots:
            for stage, ms in snap["cycle_profile_ms"].items():
                assert np.isfinite(ms) and ms >= 0.0, (stage, ms)

    def test_error_propagates(self):
        def predict(inputs):
            raise RuntimeError("boom")

        mb = MicroBatcher(predict, batch_timeout_s=0.01)
        with pytest.raises(RuntimeError, match="boom"):
            mb.submit({"x": np.zeros((1,))})
        mb.close()

    def test_pipelined_dispatch_overlaps_slow_predict(self):
        """With a high-latency predict (the driver-tunnel regime), two
        executors must keep two batches in flight: wall time for two
        batches' worth of load ~= one latency, not two (the round-2
        failure: one runner thread => one batch in flight => throughput
        collapse)."""
        import concurrent.futures as cf
        import time as _t

        latency = 0.15

        def predict(inputs):
            _t.sleep(latency)
            return {"y": inputs["x"]}

        mb = MicroBatcher(predict, max_batch_size=4,
                          allowed_batch_sizes=[1, 2, 4],
                          batch_timeout_s=0.02, in_flight=2)
        try:
            t0 = _t.perf_counter()
            with cf.ThreadPoolExecutor(8) as ex:
                outs = list(ex.map(
                    lambda i: mb.submit({"x": np.full((1,), float(i))}),
                    range(8),
                ))
            wall = _t.perf_counter() - t0
            assert len(outs) == 8
            # 8 requests = 2+ batches of <=4; serialized would be
            # >= 2*latency + collect timeouts; pipelined fits well under.
            assert wall < 2 * latency + 0.1, wall
        finally:
            mb.close()

    def test_stats_batch_size_distribution(self):
        def predict(inputs):
            return {"y": inputs["x"]}

        mb = MicroBatcher(predict, max_batch_size=4,
                          allowed_batch_sizes=[1, 2, 4],
                          batch_timeout_s=0.02, in_flight=2)
        try:
            import concurrent.futures as cf

            with cf.ThreadPoolExecutor(8) as ex:
                list(ex.map(
                    lambda i: mb.submit({"x": np.full((1,), float(i))}),
                    range(8),
                ))
            stats = mb.stats()
            assert stats["requests"] == 8
            assert stats["batches"] >= 2
            assert sum(k * v for k, v in
                       stats["batch_size_hist"].items()) == 8
            assert stats["mean_batch_size"] > 0
        finally:
            mb.close()


class TestGRPC:
    def test_predict_classify_metadata_roundtrip(self, exported):
        import grpc

        from kubeflow_tpu.serving.grpc_server import (
            PredictionClient,
            make_grpc_server,
        )

        base, model, variables = exported
        srv = ModelServer()
        srv.add_model("tiny", str(base))
        server = make_grpc_server(srv, port=0, host="127.0.0.1")
        try:
            client = PredictionClient(f"127.0.0.1:{server.bound_port}")
            rng = np.random.RandomState(9)
            img = rng.randn(2, IMG, IMG, 3).astype(np.float32)
            out = client.predict("tiny", {"image": img})
            assert out["scores"].shape == (2, CLASSES)
            np.testing.assert_allclose(out["scores"].sum(-1), 1.0, atol=1e-3)

            pairs = client.classify("tiny", {"image": img})
            assert len(pairs) == 2 and len(pairs[0]) == 2  # top_k=2 config

            meta = client.metadata("tiny")
            assert meta["version"] == 1

            with pytest.raises(grpc.RpcError) as err:
                client.predict("missing", {"image": img})
            assert err.value.code() == grpc.StatusCode.NOT_FOUND
            client.close()
        finally:
            server.stop(0)

    def test_server_span_continues_client_traceparent(self, exported):
        """The gRPC face reads ``traceparent`` from invocation
        metadata: the server span joins the caller's trace (consistent
        trace_id, parent = the caller's span id) and the admission
        child span hangs under it."""
        import grpc

        from kubeflow_tpu.runtime import tracing
        from kubeflow_tpu.serving import grpc_server as gs

        base, _, _ = exported
        srv = ModelServer()
        srv.add_model("tiny", str(base))
        server = gs.make_grpc_server(srv, port=0, host="127.0.0.1")
        store = tracing.enable(sample_rate=1.0)
        try:
            channel = grpc.insecure_channel(
                f"127.0.0.1:{server.bound_port}")
            method = channel.unary_unary(
                f"/{gs.SERVICE}/Predict",
                request_serializer=(
                    gs.pb.PredictRequest.SerializeToString),
                response_deserializer=gs.pb.PredictResponse.FromString)
            req = gs.pb.PredictRequest()
            req.model_spec.name = "tiny"
            rng = np.random.RandomState(9)
            req.inputs["image"].CopyFrom(gs.numpy_to_tensor(
                rng.randn(1, IMG, IMG, 3).astype(np.float32)))
            trace_id = tracing.new_trace_id()
            parent_id = tracing.new_span_id()
            header = tracing.format_traceparent(trace_id, parent_id)
            method(req, timeout=60,
                   metadata=(("traceparent", header),))
            channel.close()
            traces = [t for t in store.traces()
                      if t["trace_id"] == trace_id]
            assert len(traces) == 1, store.traces()
            spans = {s["name"]: s for s in traces[0]["spans"]}
            assert spans["server.grpc_predict"]["parent_id"] \
                == parent_id
            assert spans["server.admission"]["parent_id"] \
                == spans["server.grpc_predict"]["span_id"]
        finally:
            tracing.disable()
            server.stop(0)

    def test_health_check_mirrors_readyz(self, exported):
        """grpc.health.v1 Check parity with /readyz: SERVING with a
        model loaded, NOT_SERVING once a drain begins — so the fleet
        router can probe gRPC-only replicas (satellite of the fleet
        control plane)."""
        from kubeflow_tpu.serving.grpc_server import (
            PredictionClient,
            check_health,
            make_grpc_server,
        )

        base, _, _ = exported
        srv = ModelServer()
        srv.add_model("tiny", str(base))
        server = make_grpc_server(srv, port=0, host="127.0.0.1")
        try:
            target = f"127.0.0.1:{server.bound_port}"
            assert check_health(target) is True
            client = PredictionClient(target)
            assert client.ready() is True
            srv.begin_drain()  # /readyz flips 503 -> Check NOT_SERVING
            assert client.ready() is False
            assert check_health(target) is False
            client.close()
        finally:
            server.stop(0)
            srv._draining.clear()

    def test_health_check_unreachable_is_false_not_raise(self):
        from kubeflow_tpu.serving.grpc_server import check_health

        # A probe's job is a verdict: no listener -> False.
        assert check_health("127.0.0.1:1", timeout=0.5) is False


class TestRetryCallHonorsServerHint:
    def test_overloaded_waits_server_retry_after(self):
        import random

        from kubeflow_tpu.serving.grpc_server import retry_call
        from kubeflow_tpu.serving.model_server import Overloaded

        sleeps = []
        calls = []

        def fn():
            calls.append(1)
            if len(calls) < 3:
                raise Overloaded("full", retry_after_s=2.0)
            return "ok"

        out = retry_call(fn, retries=3, backoff_s=0.001,
                         backoff_cap_s=10.0, rng=random.Random(0),
                         sleep=sleeps.append)
        assert out == "ok" and len(calls) == 3
        # Both waits came from the server's 2.0s hint (±10% jitter),
        # not the 1ms local schedule.
        assert all(2.0 <= s <= 2.2 + 1e-9 for s in sleeps), sleeps

    def test_hint_capped_and_deadline_never_retried(self):
        import random

        from kubeflow_tpu.serving.errors import DeadlineExceeded
        from kubeflow_tpu.serving.grpc_server import retry_call
        from kubeflow_tpu.serving.model_server import Overloaded

        sleeps = []

        def overloaded():
            raise Overloaded("full", retry_after_s=3600.0)

        with pytest.raises(Overloaded):
            retry_call(overloaded, retries=1, backoff_cap_s=0.05,
                       rng=random.Random(0), sleep=sleeps.append)
        assert sleeps and sleeps[0] <= 0.055 + 1e-9  # capped hint

        calls = []

        def expired():
            calls.append(1)
            raise DeadlineExceeded("spent")

        with pytest.raises(DeadlineExceeded):
            retry_call(expired, retries=5, sleep=sleeps.append)
        assert len(calls) == 1  # the deadline is spent; no retry


class TestLoaderAllowlist:
    """model.json is producer-controlled: loader resolution must not
    import arbitrary modules (ADVICE r1: code-exec via writable model
    path)."""

    def test_unlisted_module_rejected(self):
        from kubeflow_tpu.serving.export import resolve_loader

        with pytest.raises(PermissionError):
            resolve_loader("os:system")

    def test_builtin_loaders_allowed(self):
        from kubeflow_tpu.serving.export import resolve_loader

        fn = resolve_loader("kubeflow_tpu.serving.loaders:classifier")
        assert callable(fn)

    def test_registered_name_wins(self):
        from kubeflow_tpu.serving.export import (
            register_loader,
            resolve_loader,
        )

        sentinel = lambda cfg: None
        register_loader("my-loader", sentinel)
        assert resolve_loader("my-loader") is sentinel

    def test_opt_in_module(self, monkeypatch):
        from kubeflow_tpu.serving.export import resolve_loader

        monkeypatch.setenv("KFT_SERVING_LOADER_MODULES", "json")
        assert callable(resolve_loader("json:loads"))


class TestBatcherPadTable:
    def test_max_batch_clamped_to_pad_table(self):
        """max_batch_size beyond the padding table would produce unpadded
        batches and fresh compiles; the cap is the table max."""
        calls = []

        def predict(inputs):
            calls.append(inputs["x"].shape[0])
            return {"y": inputs["x"]}

        b = MicroBatcher(predict, max_batch_size=8,
                         allowed_batch_sizes=[1, 2, 4],
                         batch_timeout_s=0.01)
        try:
            assert b.max_batch_size == 4
            import concurrent.futures as cf

            with cf.ThreadPoolExecutor(8) as ex:
                outs = list(ex.map(
                    lambda i: b.submit({"x": np.full((1, 2), i)}), range(8)
                ))
            assert len(outs) == 8
            assert all(c in (1, 2, 4) for c in calls)  # never unpadded 8
        finally:
            b.close()


class TestShapeGroupedBatching:
    def test_mixed_shapes_batch_separately_and_all_succeed(self):
        """One odd-shaped request must not poison the batch: rows only
        share a device batch with shape-identical peers (LM prompts come
        in many lengths)."""
        shapes_seen = []

        def predict(inputs):
            shapes_seen.append(inputs["x"].shape)
            return {"y": inputs["x"] * 2}

        mb = MicroBatcher(predict, max_batch_size=8, batch_timeout_s=0.05,
                          allowed_batch_sizes=[1, 2, 4, 8])
        results = {}

        def worker(i):
            width = 2 if i % 2 == 0 else 3   # two shape groups
            results[i] = mb.submit({"x": np.full((1, width), float(i))})

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        mb.close()
        for i in range(8):
            width = 2 if i % 2 == 0 else 3
            np.testing.assert_allclose(
                results[i]["y"], np.full((1, width), 2.0 * i))
        # No device batch ever mixed the two widths.
        assert all(s[1] in (2, 3) for s in shapes_seen)
        assert {s[1] for s in shapes_seen} == {2, 3}

    def test_lm_generate_batches_uniform_prompts(self, tmp_path):
        """Uniform-length decode requests coalesce into one batched
        generate program and every caller gets its own row back."""
        import jax
        import jax.numpy as jnp

        from kubeflow_tpu.models.transformer import (
            Transformer,
            TransformerConfig,
        )
        from kubeflow_tpu.serving.export import export

        cfg = TransformerConfig(
            vocab_size=64, d_model=16, n_layers=1, n_heads=2, n_kv_heads=2,
            d_ff=32, head_dim=8, max_seq_len=32, dtype=jnp.float32)
        model = Transformer(cfg)
        variables = model.init(jax.random.key(0),
                               jnp.zeros((1, 4), jnp.int32))
        export(str(tmp_path / "lm"), 1, variables,
               loader="kubeflow_tpu.serving.loaders:lm_generate",
               config={"model": {
                   "vocab_size": 64, "d_model": 16, "n_layers": 1,
                   "n_heads": 2, "n_kv_heads": 2, "d_ff": 32,
                   "head_dim": 8, "max_seq_len": 32, "dtype": "float32"},
                   "max_new_tokens": 4, "temperature": 0.0})
        server = ModelServer()
        server.add_model("lm", str(tmp_path / "lm"))
        predict = server.get("lm").predict

        prompts = [np.random.RandomState(i).randint(1, 64, (1, 4))
                   .astype(np.int32) for i in range(4)]
        direct = [np.asarray(predict({"tokens": p})["tokens"])
                  for p in prompts]

        mb = MicroBatcher(predict, max_batch_size=4, batch_timeout_s=0.1,
                          allowed_batch_sizes=[1, 2, 4])
        results = {}

        def worker(i):
            results[i] = mb.submit({"tokens": prompts[i]})

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        stats = mb.stats()
        mb.close()
        for i in range(4):
            np.testing.assert_array_equal(
                np.asarray(results[i]["tokens"]), direct[i])
        assert stats["mean_batch_size"] > 1, stats


class TestDispatchFairness:
    """_take_batch_locked liveness: a saturating majority shape must not
    starve an expired minority shape (full groups get no priority over
    older expired heads)."""

    @staticmethod
    def _bare(max_batch_size=2, timeout=10.0):
        # Construct the object without starting runner threads so the
        # dispatch choice is deterministic and directly observable.
        mb = object.__new__(MicroBatcher)
        mb.max_batch_size = max_batch_size
        mb.batch_timeout_s = timeout
        mb._groups = {}
        mb._next_deadline = None
        mb._stopped = False
        mb._pending_total = 0
        return mb

    @staticmethod
    def _entry(t, id_, deadline=None):
        return {"t": t, "id": id_, "deadline": deadline}

    def test_expired_minority_beats_full_majority(self):
        import time as _t

        mb = self._bare(max_batch_size=2, timeout=0.01)
        now = _t.monotonic()
        # Majority shape A: full group, fresh heads (sustained load).
        mb._groups["A"] = [self._entry(now, i) for i in range(2)]
        # Minority shape B: one entry, long expired.
        mb._groups["B"] = [self._entry(now - 1.0, "b")]
        batch = mb._take_batch_locked([])
        assert [e["id"] for e in batch] == ["b"], batch

    def test_full_group_dispatches_before_its_own_timeout(self):
        import time as _t

        mb = self._bare(max_batch_size=2, timeout=10.0)
        now = _t.monotonic()
        mb._groups["A"] = [self._entry(now, 0), self._entry(now, 1)]
        mb._groups["B"] = [self._entry(now, "b")]  # neither full nor old
        batch = mb._take_batch_locked([])
        assert [e["id"] for e in batch] == [0, 1]
        # B stays queued with its own deadline registered.
        assert "B" in mb._groups and mb._next_deadline is not None

    def test_nothing_ready_registers_earliest_deadline(self):
        import time as _t

        mb = self._bare(max_batch_size=4, timeout=10.0)
        now = _t.monotonic()
        mb._groups["A"] = [self._entry(now, 0)]
        mb._groups["B"] = [self._entry(now - 5.0, "b")]  # older, not expired
        batch = mb._take_batch_locked([])
        assert batch is None
        # Earliest deadline is B's (older head).
        assert abs(mb._next_deadline - (now - 5.0 + 10.0)) < 0.5

    def test_request_deadline_swept_before_dispatch(self):
        """A deadline-expired entry is swept into the expired list, not
        dispatched — even when its group is otherwise dispatchable."""
        import time as _t

        mb = self._bare(max_batch_size=2, timeout=0.01)
        now = _t.monotonic()
        mb._pending_total = 2
        mb._groups["A"] = [
            self._entry(now - 1.0, "dead", deadline=now - 0.5),
            self._entry(now - 1.0, "live"),
        ]
        expired = []
        batch = mb._take_batch_locked(expired)
        assert [e["id"] for e in expired] == ["dead"]
        assert [e["id"] for e in batch] == ["live"]
        assert mb._pending_total == 0


class TestDeployedBatching:
    """ModelServer.enable_batching: the deployed predict path (REST via
    http.py and gRPC via grpc_server.py both route through
    ModelServer.predict) coalesces concurrent single-row requests,
    survives hot-swap, and leaves multi-row, pinned-version, and
    over-bucket requests on the direct path."""

    def _counting_factory(self, calls):
        from kubeflow_tpu.serving.model_server import MicroBatcher

        def build(model):
            def predict(inputs):
                calls.append(inputs["image"].shape[0])
                return model.predict(inputs)

            return MicroBatcher(predict, max_batch_size=4,
                                batch_timeout_s=0.25,
                                allowed_batch_sizes=[1, 2, 4],
                                name=f"t-v{model.version}")

        return build

    def test_concurrent_singles_coalesce_and_swap_keeps_batching(
            self, exported, tmp_path):
        base, model, variables = exported
        srv = ModelServer()
        srv.add_model("tiny", str(base))
        calls = []
        srv.enable_batching("tiny", self._counting_factory(calls))
        try:
            img = np.zeros((1, IMG, IMG, 3), np.float32)

            def one(i):
                return srv.predict("tiny", {"image": img + i * 0.01})

            # Warm the predict compile first so the concurrent arrivals
            # are not staggered by it (the generous 250 ms window plus
            # this keeps the coalescing assertion timing-robust).
            one(0)
            calls.clear()

            import concurrent.futures as cf

            with cf.ThreadPoolExecutor(4) as ex:
                outs = list(ex.map(one, range(4)))
            assert all(o["scores"].shape == (1, CLASSES) for o in outs)
            assert len(calls) < 4, "requests were not coalesced"

            # Hot-swap to version 2: batching must keep working through
            # the rebuilt batcher (no restart, no stale predict).
            export(base, 2, variables,
                   loader="kubeflow_tpu.serving.loaders:classifier",
                   config={"family": "resnet18", "num_classes": CLASSES,
                           "top_k": 2, "num_filters": 8})
            assert srv.reload("tiny")
            out = srv.predict("tiny", {"image": img})
            assert out["scores"].shape == (1, CLASSES)

            # Multi-row requests bypass the batcher (an entry maps to
            # exactly one result row); pinned versions bypass too.
            n_calls = len(calls)
            batch = srv.predict("tiny",
                                {"image": np.zeros((3, IMG, IMG, 3),
                                                   np.float32)})
            assert batch["scores"].shape == (3, CLASSES)
            pinned = srv.predict("tiny", {"image": img}, version=2)
            assert pinned["scores"].shape == (1, CLASSES)
        finally:
            srv.stop()


def test_main_batcher_factory_picks_per_loader():
    from kubeflow_tpu.serving.main import batcher_factory
    from kubeflow_tpu.serving.model_server import (
        BucketedLMBatcher,
        LoadedModel,
        MicroBatcher,
    )

    build = batcher_factory(micro_batch_size=8, batch_timeout_s=0.005,
                            lm_buckets="64,128")
    lm = LoadedModel(name="lm", version=1, predict=lambda i: i,
                     meta={"loader":
                           "kubeflow_tpu.serving.loaders:lm_generate"})
    clf = LoadedModel(name="clf", version=1, predict=lambda i: i,
                      meta={"loader":
                            "kubeflow_tpu.serving.loaders:classifier"})
    b_lm, b_clf = build(lm), build(clf)
    try:
        assert isinstance(b_lm, BucketedLMBatcher)
        assert b_lm.buckets == [64, 128]
        assert isinstance(b_clf, MicroBatcher)
        assert b_clf.max_batch_size == 8
    finally:
        b_lm.close()
        b_clf.close()

    # Without buckets even an lm model gets the plain batcher.
    build2 = batcher_factory(micro_batch_size=4, batch_timeout_s=0.005)
    b2 = build2(lm)
    try:
        assert isinstance(b2, MicroBatcher)
    finally:
        b2.close()


class TestBatcherLifecycleRaces:
    def test_submit_after_close_raises_not_hangs(self):
        from kubeflow_tpu.serving.model_server import BatcherClosed

        mb = MicroBatcher(lambda i: i, batch_timeout_s=0.01)
        mb.close()
        with pytest.raises(BatcherClosed):
            mb.submit({"x": np.zeros((1, 2))})

    def test_predict_retries_onto_replacement_batcher(self, exported):
        """A hot-swap can close the batcher between lookup and submit;
        predict must retry against the rebuilt one, not hang or fail."""
        from kubeflow_tpu.serving.model_server import (
            BatcherClosed,
            MicroBatcher,
        )

        base, _, _ = exported
        srv = ModelServer()
        srv.add_model("tiny", str(base))

        model = srv.get("tiny")
        real = MicroBatcher(model.predict, max_batch_size=2,
                            batch_timeout_s=0.01,
                            allowed_batch_sizes=[1, 2], name="real")

        class ClosedOnce:
            calls = 0

            def submit(self, inputs):
                # Simulate reload() winning the race: the replacement is
                # installed, then this stale batcher reports closed.
                ClosedOnce.calls += 1
                srv._batchers["tiny"] = real
                raise BatcherClosed("stale")

            def close(self):
                pass

        srv._batchers["tiny"] = ClosedOnce()
        try:
            out = srv.predict(
                "tiny",
                {"image": np.zeros((1, IMG, IMG, 3), np.float32)})
            assert out["scores"].shape == (1, CLASSES)
            assert ClosedOnce.calls == 1
        finally:
            real.close()
            srv.stop()

    def test_finish_failure_spares_delivered_rows(self):
        """A `finish` hook raising on row i must not poison rows
        0..i-1 of the same batch: their waiters keep their results
        (they may not have woken yet when the error handler runs)."""
        from kubeflow_tpu.serving.model_server import MicroBatcher

        def finish(row, meta):
            if meta:
                raise RuntimeError("finish boom")
            return row

        mb = MicroBatcher(
            lambda inputs: {"x": np.asarray(inputs["x"])},
            max_batch_size=2, batch_timeout_s=0.5,
            allowed_batch_sizes=[1, 2], in_flight=1, name="finfail",
            group_key=lambda inputs: "all",
            collate=lambda rows: (
                {"x": np.concatenate(
                    [np.asarray(r["x"]) for r in rows], axis=0)},
                # Meta truthy (=> finish raises) for every row but the
                # first, so one batch mixes delivered and poisoned rows.
                [i > 0 for i in range(len(rows))]),
            finish=finish,
        )
        try:
            import concurrent.futures as cf

            with cf.ThreadPoolExecutor(2) as ex:
                futs = [ex.submit(
                    mb.submit, {"x": np.full((1, 2), i, np.int32)})
                    for i in range(2)]
                results = []
                for f in futs:
                    try:
                        results.append(("ok", f.result(timeout=10)))
                    except RuntimeError as exc:
                        results.append(("err", str(exc)))
            kinds = sorted(k for k, _ in results)
            # Exactly one row delivered, one poisoned — never both
            # poisoned (the old handler overwrote delivered rows) and
            # never a hang.
            assert kinds == ["err", "ok"], results
        finally:
            mb.close()

    def test_over_bucket_prompt_falls_back_to_direct(self):
        from kubeflow_tpu.serving.model_server import BucketedLMBatcher

        served = []

        def predict(inputs):
            served.append(np.asarray(inputs["tokens"]).shape)
            return {"tokens": np.asarray(inputs["tokens"])}

        srv = ModelServer()
        srv._models["lm"] = {1: __import__(
            "kubeflow_tpu.serving.model_server",
            fromlist=["LoadedModel"]).LoadedModel(
                name="lm", version=1, predict=predict, meta={})}
        srv._base_paths["lm"] = "unused"
        bmb = BucketedLMBatcher(predict, buckets=[8], name="over")
        srv._batchers["lm"] = bmb
        try:
            out = srv.predict("lm", {"tokens": np.zeros((1, 20),
                                                        np.int32)})
            # Served directly at its natural length, unpadded, unerrored.
            assert out["tokens"].shape == (1, 20)
            assert served[-1] == (1, 20)
        finally:
            bmb.close()
            srv.stop()

    def test_per_request_budget_trims_batched_rows(self):
        """A per-request max_new_tokens must be honored on the static
        batcher path: the generate program still decodes the config's
        full budget (it is baked into the program), but each row's
        surplus is trimmed on the way out — same contract as the
        DecodeEngine and the direct path."""
        import concurrent.futures as cf

        from kubeflow_tpu.serving.model_server import BucketedLMBatcher

        config_new = 10

        def predict(inputs):
            toks = np.asarray(inputs["tokens"])
            fill = np.full((toks.shape[0], config_new), 7, toks.dtype)
            return {"tokens": np.concatenate([toks, fill], axis=1)}

        bmb = BucketedLMBatcher(
            predict, buckets=[8], max_batch_size=2, batch_timeout_s=0.2,
            allowed_batch_sizes=[1, 2], name="budget")
        try:
            with cf.ThreadPoolExecutor(2) as ex:
                small = ex.submit(bmb.submit, {
                    "tokens": np.ones((1, 3), np.int32),
                    "max_new_tokens": 2})
                full = ex.submit(bmb.submit, {
                    "tokens": np.ones((1, 8), np.int32)})
                # Row with a budget: prompt 3 + 2 new, pad stripped.
                assert small.result(timeout=30)["tokens"].shape == (1, 5)
                # Row without one keeps the config budget untouched.
                assert full.result(timeout=30)["tokens"].shape \
                    == (1, 8 + config_new)
        finally:
            bmb.close()


class TestIdempotencyDedup:
    """ModelServer's idempotency-key result dedup (PR 14): a retried
    key is answered, never re-executed — the survivable-inference
    contract behind the router's POST replays."""

    def _server(self, predict, **kw):
        from kubeflow_tpu.serving.model_server import LoadedModel

        server = ModelServer(**kw)
        server._models["m"] = {1: LoadedModel(
            name="m", version=1, predict=predict, meta={})}
        return server

    def test_completed_duplicate_answered_from_cache(self):
        calls = []

        def predict(inputs):
            calls.append(1)
            return {"y": np.asarray([len(calls)])}

        server = self._server(predict)
        inp = {"x": np.asarray([[1.0]])}
        r1 = server.predict("m", inp, idem_key="k1")
        r2 = server.predict("m", inp, idem_key="k1")
        assert len(calls) == 1
        # The IDENTICAL payload, not a fresh execution's.
        assert r1 is r2
        # A different key is a different request.
        server.predict("m", inp, idem_key="k2")
        assert len(calls) == 2
        # No key = no dedup (the pre-PR-14 path, unchanged).
        server.predict("m", inp)
        assert len(calls) == 3
        from kubeflow_tpu.runtime.prom import (
            REGISTRY,
            parse_metrics,
            sample_value,
        )

        parsed = parse_metrics(REGISTRY.render())
        assert (sample_value(parsed, "kft_serving_dedup_hits_total",
                             model="m") or 0) >= 1

    def test_concurrent_double_submit_executes_once(self):
        import time as _time

        started = threading.Event()
        release = threading.Event()
        calls = []

        def predict(inputs):
            calls.append(1)
            started.set()
            release.wait(timeout=10)
            return {"y": np.asarray([7])}

        server = self._server(predict)
        inp = {"x": np.asarray([[1.0]])}
        results = {}

        def submit(i):
            results[i] = server.predict("m", inp, idem_key="dup")

        t1 = threading.Thread(target=submit, args=(0,))
        t1.start()
        assert started.wait(timeout=10)
        # The duplicate arrives while the primary is mid-execution:
        # it must ATTACH, not run predict a second time.
        t2 = threading.Thread(target=submit, args=(1,))
        t2.start()
        _time.sleep(0.05)
        release.set()
        t1.join(timeout=10)
        t2.join(timeout=10)
        assert len(calls) == 1, "double submit executed twice"
        assert results[0] is results[1]

    def test_failures_are_not_cached(self):
        calls = []

        def predict(inputs):
            calls.append(1)
            if len(calls) == 1:
                raise RuntimeError("transient")
            return {"y": np.asarray([1])}

        server = self._server(predict)
        inp = {"x": np.asarray([[1.0]])}
        with pytest.raises(RuntimeError):
            server.predict("m", inp, idem_key="k")
        # The key freed with the failure: the retry re-executes.
        out = server.predict("m", inp, idem_key="k")
        assert len(calls) == 2
        assert int(np.asarray(out["y"])[0]) == 1

    def test_ttl_expires_completed_results(self):
        from kubeflow_tpu.testing import faults

        calls = []

        def predict(inputs):
            calls.append(1)
            return {"y": np.asarray([len(calls)])}

        server = self._server(predict, dedup_ttl_s=30.0)
        inp = {"x": np.asarray([[1.0]])}
        with faults.injected("seed=0") as inj:
            server.predict("m", inp, idem_key="k")
            server.predict("m", inp, idem_key="k")
            assert len(calls) == 1
            # Past the TTL (policy clock) the key re-executes: a
            # cached result must not outlive its usefulness window.
            inj.advance_clock(31)
            server.predict("m", inp, idem_key="k")
            assert len(calls) == 2

    def test_capacity_evicts_completed_not_inflight(self):
        from kubeflow_tpu.serving.model_server import _DedupCache

        cache = _DedupCache(capacity=2, ttl_s=0)
        v1, e1 = cache.begin("a")
        cache.finish("a", e1, {"r": 1})
        v2, e2 = cache.begin("b")  # in flight
        v3, e3 = cache.begin("c")  # overflows: evicts completed "a"
        assert (v1, v2, v3) == ("new", "new", "new")
        assert cache.begin("a")[0] == "new"  # evicted
        # The in-flight entry is pinned (waiters hold it).
        assert cache.begin("b")[0] == "inflight"

    def test_grpc_metadata_key_dedups(self, exported):
        """The gRPC face's x-kft-idempotency-key metadata reaches the
        same dedup cache the REST header feeds."""
        from kubeflow_tpu.serving.grpc_server import (
            PredictionClient,
            make_grpc_server,
        )

        base, _, _ = exported
        calls = []
        server = ModelServer()
        server.add_model("resnet", str(base))
        real = server.get("resnet").predict

        def counting(inputs):
            calls.append(1)
            return real(inputs)

        server.get("resnet").predict = counting
        grpc_server = make_grpc_server(server, port=0,
                                       host="127.0.0.1")
        client = PredictionClient(
            f"127.0.0.1:{grpc_server.bound_port}")
        try:
            img = np.zeros((1, 32, 32, 3), np.float32)
            r1 = client.predict("resnet", {"image": img},
                                idem_key="g1")
            r2 = client.predict("resnet", {"image": img},
                                idem_key="g1")
            assert len(calls) == 1
            for k in r1:
                assert np.array_equal(r1[k], r2[k])
        finally:
            client.close()
            grpc_server.stop(grace=0)
            server.stop()

    def test_rest_header_key_dedups(self, exported):
        """The REST x-kft-idempotency-key header reaches the dedup
        cache and the duplicate answers BYTE-identical."""
        from kubeflow_tpu.serving.http import make_http_server

        base, _, _ = exported
        calls = []
        server = ModelServer()
        server.add_model("resnet", str(base))
        real = server.get("resnet").predict

        def counting(inputs):
            calls.append(1)
            return real(inputs)

        server.get("resnet").predict = counting
        httpd = None
        try:
            httpd, _ = make_http_server(server, port=0,
                                        host="127.0.0.1")
            port = httpd.server_address[1]
            body = json.dumps({"instances": [
                {"image": np.zeros((32, 32, 3)).tolist()}]}).encode()

            def post():
                req = urllib.request.Request(
                    f"http://127.0.0.1:{port}/model/resnet:predict",
                    data=body,
                    headers={"X-KFT-Idempotency-Key": "rest-1"})
                with urllib.request.urlopen(req, timeout=60) as resp:
                    return resp.read()

            p1 = post()
            p2 = post()
            assert len(calls) == 1
            assert p1 == p2
        finally:
            if httpd is not None:
                httpd.shutdown()
            server.stop()
