"""Container entrypoints executed for real: launcher (heir of the
reference's tf-cnn launcher.py), the LM training entrypoint, and the
profiling helpers — the last modules that had no direct test."""

import os
import pathlib
import subprocess
import sys

import pytest

REPO = pathlib.Path(__file__).parents[1]


def _env():
    # Same hermetic-spawn rationale as test_serving_process.py.
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=str(REPO),
               XLA_FLAGS="--xla_force_host_platform_device_count=4")
    return env


class TestLauncher:
    def test_exec_command_propagates_exit_code(self):
        ok = subprocess.run(
            [sys.executable, "-m", "kubeflow_tpu.tools.launcher",
             "--no-distributed", "--",
             sys.executable, "-c", "print('worker ran')"],
            capture_output=True, text=True, timeout=240, env=_env(),
        )
        assert ok.returncode == 0, ok.stderr[-1500:]
        assert "worker ran" in ok.stdout

        fail = subprocess.run(
            [sys.executable, "-m", "kubeflow_tpu.tools.launcher",
             "--no-distributed", "--",
             sys.executable, "-c", "raise SystemExit(3)"],
            capture_output=True, text=True, timeout=240, env=_env(),
        )
        # The reference's launcher slept forever to mask failure
        # (tf-cnn/launcher.py:86-90); this one propagates it.
        assert fail.returncode == 3

    def test_nothing_to_run_is_an_error(self):
        proc = subprocess.run(
            [sys.executable, "-m", "kubeflow_tpu.tools.launcher",
             "--no-distributed"],
            capture_output=True, text=True, timeout=240, env=_env(),
        )
        assert proc.returncode == 2


class TestTrainLM:
    def test_few_steps_on_fake_slice(self):
        proc = subprocess.run(
            [sys.executable, "-m", "kubeflow_tpu.tools.train_lm",
             "--d-model", "32", "--n-layers", "2", "--n-heads", "4",
             "--n-kv-heads", "4", "--d-ff", "64", "--head-dim", "8",
             "--vocab-size", "64", "--seq-len", "16",
             "--batch-size-per-device", "2", "--steps", "4", "--ce-dtype", "compute",
             "--log-every", "2", "--mesh", "fsdp=2"],
            capture_output=True, text=True, timeout=280, env=_env(),
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        assert '"event": "train_step"' in proc.stderr

    def test_pipeline_parallel_on_fake_slice(self):
        """The container entrypoint trains the real LM through GPipe:
        --mesh pipeline=2 + --pipeline-microbatches, end to end."""
        proc = subprocess.run(
            [sys.executable, "-m", "kubeflow_tpu.tools.train_lm",
             "--d-model", "32", "--n-layers", "2", "--n-heads", "4",
             "--n-kv-heads", "4", "--d-ff", "64", "--head-dim", "8",
             "--vocab-size", "64", "--seq-len", "16",
             "--batch-size-per-device", "1", "--steps", "2",
             "--pipeline-microbatches", "4",
             "--log-every", "1", "--mesh", "data=2,pipeline=2"],
            capture_output=True, text=True, timeout=280, env=_env(),
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        assert '"event": "train_step"' in proc.stderr


class TestProfiling:
    def test_trace_writes_xplane(self, tmp_path):
        import jax
        import jax.numpy as jnp

        from kubeflow_tpu.runtime import profiling

        with profiling.trace(str(tmp_path)):
            jax.block_until_ready(jnp.ones((8, 8)) @ jnp.ones((8, 8)))
        files = list(tmp_path.rglob("*.xplane.pb"))
        assert files, list(tmp_path.rglob("*"))

    def test_schedule_captures_configured_window(self, tmp_path):
        import jax
        import jax.numpy as jnp

        from kubeflow_tpu.runtime.profiling import ProfileSchedule

        sched = ProfileSchedule(str(tmp_path), start=1, count=2)
        for step in range(4):
            sched.before_step(step)
            jax.block_until_ready(jnp.ones((4, 4)) * step)
            sched.after_step(step)
        sched.close()
        assert list(tmp_path.rglob("*.xplane.pb")), \
            list(tmp_path.rglob("*"))


class TestXplaneSummary:
    @pytest.mark.slow  # ~19s real-trace capture; trace-writing stays tier-1
    def test_summarizes_a_real_trace(self, tmp_path):
        import jax
        import jax.numpy as jnp

        from kubeflow_tpu.runtime import profiling

        with profiling.trace(str(tmp_path)):
            jax.block_until_ready(
                jnp.ones((64, 64)) @ jnp.ones((64, 64)))
        traces = list(tmp_path.rglob("*.xplane.pb"))
        assert traces
        proc = subprocess.run(
            [sys.executable, "-m", "kubeflow_tpu.tools.xplane_summary",
             str(traces[0]), "5", "--steps", "1"],
            capture_output=True, text=True, timeout=240, env=_env(),
        )
        assert proc.returncode == 0, proc.stderr[-1500:]
        assert "busy (leaf ops)" in proc.stdout or "plane:" in proc.stderr
