"""kft-analyze: per-checker fixtures, suppressions, baseline workflow,
CLI, and the KFT_LOCKCHECK runtime lock-order sanitizer.

Each checker gets (at least) a positive fire, a negative control, and
a suppression-honored case; the baseline tests prove shrink-only
enforcement end to end through the real CLI."""

import json
import pathlib
import subprocess
import sys
import textwrap
import threading

from kubeflow_tpu.analysis import analyze_source, core
from kubeflow_tpu.analysis.clock import ClockDiscipline
from kubeflow_tpu.analysis.jitpurity import JitPurity
from kubeflow_tpu.analysis.locks import LockGuard
from kubeflow_tpu.analysis.metrics import MetricHygiene

REPO = pathlib.Path(__file__).resolve().parent.parent

POLICY = "kubeflow_tpu/serving/mod.py"


def _src(s: str) -> str:
    return '"""mod."""\n' + textwrap.dedent(s)


class TestClockDiscipline:
    def test_fires_on_policy_module(self):
        found = analyze_source(_src("""
            import time


            def drain():
                return time.monotonic() + 5
        """), rel=POLICY)
        assert [f.check for f in found] == ["clock-discipline"]
        assert "faults.monotonic" in found[0].message
        assert found[0].symbol == "time.monotonic@drain"

    def test_time_time_also_banned(self):
        found = analyze_source(_src("""
            import time

            STAMP = time.time()
        """), rel=POLICY)
        assert [f.symbol for f in found] == ["time.time@<module>"]

    def test_perf_counter_and_sleep_stay_legal(self):
        found = analyze_source(_src("""
            import time


            def measure():
                t0 = time.perf_counter()
                time.sleep(0.01)
                return time.perf_counter() - t0
        """), rel=POLICY)
        assert found == []

    def test_non_policy_module_exempt(self):
        found = analyze_source(_src("""
            import time


            def wait():
                return time.monotonic()
        """), rel="kubeflow_tpu/runtime/mod.py")
        assert found == []

    def test_same_line_suppression(self):
        found = analyze_source(_src("""
            import time

            T = time.time()  # kft: allow=clock-discipline
        """), rel=POLICY)
        assert found == []

    def test_preceding_comment_suppression(self):
        found = analyze_source(_src("""
            import time

            # wall-clock stamp leaving the process
            # kft: allow=clock-discipline
            T = time.time()
        """), rel=POLICY)
        assert found == []


LOCK_CLASS = """
    import threading


    class C:
        def __init__(self):
            self._lock = threading.Lock()
            self.x = 0

        def bump(self):
            with self._lock:
                self.x += 1
"""


class TestLockGuard:
    def test_bare_write_of_guarded_attr_fires(self):
        found = analyze_source(_src(LOCK_CLASS + """
        def reset(self):
            self.x = 0
    """), rel=POLICY)
        assert [f.check for f in found] == ["lock-guard"]
        assert "C.x" in found[0].message
        assert found[0].symbol == "C.x@reset"

    def test_locked_suffix_method_is_lock_context(self):
        found = analyze_source(_src(LOCK_CLASS + """
        def _reset_locked(self):
            self.x = 0
    """), rel=POLICY)
        assert found == []

    def test_init_writes_never_count(self):
        found = analyze_source(_src(LOCK_CLASS), rel=POLICY)
        assert found == []

    def test_unguarded_attr_writes_fine(self):
        found = analyze_source(_src(LOCK_CLASS + """
        def other(self):
            self.y = 1
    """), rel=POLICY)
        assert found == []

    def test_nested_helper_inherits_lock_state(self):
        found = analyze_source(_src("""
            import threading


            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.x = 0

                def bump(self):
                    with self._lock:
                        def helper():
                            self.x = 2
                        helper()
                        self.x += 1
        """), rel=POLICY)
        assert found == []

    def test_suppression_honored(self):
        found = analyze_source(_src(LOCK_CLASS + """
        def reset(self):
            # single-threaded by construction here
            # kft: allow=lock-guard
            self.x = 0
    """), rel=POLICY)
        assert found == []


class TestJitPurity:
    def test_partial_decorated_function_fires(self):
        found = analyze_source(_src("""
            from functools import partial

            import jax
            import time


            @partial(jax.jit, static_argnums=(0,))
            def step(n, x):
                return x + time.time()
        """), rel="kubeflow_tpu/models/mod.py")
        assert [f.check for f in found] == ["jit-purity"]
        assert "time.time" in found[0].message
        assert found[0].symbol == "time.time@step"

    def test_call_form_resolves_module_function(self):
        found = analyze_source(_src("""
            import jax
            import random


            def f(x):
                return x * random.random()


            g = jax.jit(f)
        """), rel="kubeflow_tpu/models/mod.py")
        assert [f.symbol for f in found] == ["random.random@f"]

    def test_jax_random_and_plain_functions_legal(self):
        found = analyze_source(_src("""
            import jax
            import time


            @jax.jit
            def step(x, key):
                return x + jax.random.normal(key)


            def host_loop():
                return time.perf_counter()
        """), rel="kubeflow_tpu/models/mod.py")
        assert found == []

    def test_suppression_honored(self):
        found = analyze_source(_src("""
            import jax
            import os


            @jax.jit
            def step(x):
                # kft: allow=jit-purity
                flag = os.environ.get("DEBUG")
                return x
        """), rel="kubeflow_tpu/models/mod.py")
        assert found == []


class TestMetricHygiene:
    def test_name_must_be_kft_prefixed(self):
        found = analyze_source(_src("""
            REGISTRY.counter("requests_total", "h").inc()
        """))
        assert [f.symbol for f in found] == ["name:requests_total"]

    def test_counter_must_end_total(self):
        found = analyze_source(_src("""
            REGISTRY.counter("kft_requests", "h").inc()
        """))
        assert [f.symbol for f in found] == [
            "counter-suffix:kft_requests"]

    def test_gauge_must_not_end_total(self):
        found = analyze_source(_src("""
            REGISTRY.gauge("kft_jobs_total", "h").set(1)
        """))
        assert [f.symbol for f in found] == [
            "gauge-suffix:kft_jobs_total"]

    def test_label_mismatch_across_modules(self):
        checker = MetricHygiene()
        import ast

        a = _src("""
            C = REGISTRY.counter("kft_req_total", "h")
            C.inc(model="m")
        """)
        b = _src("""
            REGISTRY.counter("kft_req_total", "h").inc(endpoint="e")
        """)
        checker.visit_module("kubeflow_tpu/a.py", ast.parse(a), a)
        checker.visit_module("kubeflow_tpu/b.py", ast.parse(b), b)
        found = checker.finish()
        assert len(found) == 1
        assert found[0].symbol.startswith("labels:kft_req_total:")
        assert "one name, one label set" in found[0].message

    def test_aggregate_plus_labeled_is_sanctioned(self):
        found = analyze_source(_src("""
            G = REGISTRY.gauge("kft_inflight", "h")
            G.set(3.0)
            G.set(1.0, model="m")
        """))
        assert found == []

    def test_constant_name_resolved(self):
        found = analyze_source(_src("""
            BAD = "kft_shed"

            REGISTRY.counter(BAD, "h").inc(model="m")
        """))
        assert [f.symbol for f in found] == ["counter-suffix:kft_shed"]

    def test_suppression_honored(self):
        found = analyze_source(_src("""
            # legacy wire name, kept for dashboard compat
            # kft: allow=metric-hygiene
            REGISTRY.counter("requests_total", "h").inc()
        """))
        assert found == []

    def test_self_attr_binding_tracked(self):
        found = analyze_source(_src("""
            class S:
                def __init__(self):
                    self._ctr = REGISTRY.counter("kft_a_total", "h")

                def hit(self):
                    self._ctr.inc(model="m")

                def miss(self):
                    self._ctr.inc(reason="r")
        """))
        assert len(found) == 1
        assert found[0].symbol.startswith("labels:kft_a_total:")


class TestBaselineAndRunner:
    def _finding(self, symbol="time.time@f"):
        return core.Finding(check="clock-discipline", path=POLICY,
                            line=3, col=0, message="m", symbol=symbol)

    def test_split_by_baseline(self):
        f_new = self._finding("new@f")
        f_old = self._finding("old@f")
        baseline = [f_old.fingerprint(), "clock-discipline::gone::x@y"]
        new, old, stale = core.split_by_baseline([f_new, f_old],
                                                 baseline)
        assert new == [f_new]
        assert old == [f_old]
        assert stale == ["clock-discipline::gone::x@y"]

    def test_dedupe_symbols_disambiguates(self):
        a, b = self._finding(), self._finding()
        out = core.dedupe_symbols([a, b])
        assert out[0].symbol == "time.time@f"
        assert out[1].symbol == "time.time@f#2"

    def test_repo_runs_clean_in_process(self):
        baseline = core.load_baseline(REPO / "ci"
                                      / "analysis_baseline.json")
        report = core.run(REPO, baseline=baseline)
        assert report.ok, [f.render() for f in report.findings] \
            + report.stale


def _scratch_repo(tmp_path, body):
    pkg = tmp_path / "kubeflow_tpu" / "serving"
    pkg.mkdir(parents=True)
    (tmp_path / "ci").mkdir()
    (pkg / "mod.py").write_text('"""mod."""\nimport time\n' + body)
    return tmp_path


def _analyze(root, *args):
    return subprocess.run(
        [sys.executable, "-m", "kubeflow_tpu.analysis",
         "--root", str(root), *args],
        capture_output=True, text=True, cwd=str(REPO))


class TestCLI:
    def test_finding_fails_run_and_renders_json(self, tmp_path):
        root = _scratch_repo(tmp_path,
                             "D = time.monotonic() + 1\n")
        proc = _analyze(root)
        assert proc.returncode == 1
        assert "clock-discipline" in proc.stdout
        proc = _analyze(root, "--json")
        assert proc.returncode == 1
        payload = json.loads(proc.stdout)
        assert payload["findings"][0]["check"] == "clock-discipline"
        assert payload["findings"][0]["path"].endswith("mod.py")

    def test_baseline_tolerates_then_shrink_only(self, tmp_path):
        root = _scratch_repo(tmp_path,
                             "D = time.monotonic() + 1\n")
        # Grandfather the finding into the baseline: run goes green.
        assert _analyze(root, "--write-baseline").returncode == 0
        proc = _analyze(root)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "1 baselined" in proc.stderr
        # Adding a NEW finding still fails — the baseline can't grow.
        mod = root / "kubeflow_tpu" / "serving" / "mod.py"
        mod.write_text(mod.read_text()
                       + "E = time.monotonic() + 2\n")
        assert _analyze(root).returncode == 1
        # Fixing the original finding makes its entry STALE: the run
        # fails until the entry is deleted (shrink-only enforcement).
        mod.write_text('"""mod."""\nimport time\n')
        proc = _analyze(root)
        assert proc.returncode == 1
        assert "stale baseline entry" in proc.stdout
        assert _analyze(root, "--write-baseline").returncode == 0
        assert _analyze(root).returncode == 0


# -- the four flow-sensitive checkers (analysis/cfg.py dataflow) ------------
#
# Each seeded-mutation test pairs a faithful copy of REAL repo code
# (which must stay clean) with a minimally-broken variant (which must
# produce exactly the expected finding) — the checker is proven on the
# code shapes it exists to guard, not on toy fixtures.

# Mirrors testing/faults.py FaultInjector.fire: the sleep runs OUTSIDE
# the lock by design.
FIRE_CLEAN = """
    import threading
    import time


    class FaultInjector:
        def __init__(self):
            self._lock = threading.Lock()
            self._fired = {}
            self._specs = {}

        def fire(self, site):
            sleep_s = 0.0
            with self._lock:
                self._fired[site] = self._fired.get(site, 0) + 1
                for s in self._specs.get(site, ()):
                    sleep_s += s.value
            if sleep_s:
                time.sleep(sleep_s)
"""

# Minimal mutation: the sleep moved INSIDE the locked region.
FIRE_MUTATED = """
    import threading
    import time


    class FaultInjector:
        def __init__(self):
            self._lock = threading.Lock()
            self._fired = {}
            self._specs = {}

        def fire(self, site):
            sleep_s = 0.0
            with self._lock:
                self._fired[site] = self._fired.get(site, 0) + 1
                for s in self._specs.get(site, ()):
                    sleep_s += s.value
                if sleep_s:
                    time.sleep(sleep_s)
"""


class TestBlockingUnderLock:
    def test_real_fire_shape_is_clean(self):
        assert analyze_source(_src(FIRE_CLEAN), rel=POLICY) == []

    def test_sleep_under_registry_lock_fires(self):
        found = analyze_source(_src(FIRE_MUTATED), rel=POLICY)
        assert [f.check for f in found] == ["blocking-under-lock"]
        assert "time.sleep" in found[0].message
        assert "self._lock" in found[0].message
        assert found[0].symbol == "time.sleep@FaultInjector.fire"

    def test_released_before_sleep_is_clean(self):
        found = analyze_source(_src("""
            import time


            class C:
                def wait(self):
                    self._lock.acquire()
                    n = self._n
                    self._lock.release()
                    time.sleep(n)
        """), rel=POLICY)
        assert found == []

    def test_locked_suffix_method_counts_as_held(self):
        found = analyze_source(_src("""
            import time


            class C:
                def _sweep_locked(self):
                    time.sleep(0.1)
        """), rel=POLICY)
        assert len(found) == 1
        assert "caller-held" in found[0].message

    def test_exception_path_releases_lock(self):
        # A raise inside the with block exits the lock before the
        # handler runs: the handler's sleep is NOT under the lock.
        found = analyze_source(_src("""
            import time


            class C:
                def step(self):
                    try:
                        with self._lock:
                            self._n += 1
                            raise ValueError("x")
                    except ValueError:
                        time.sleep(0.1)
        """), rel=POLICY)
        assert found == []

    def test_future_result_and_blocking_get_fire(self):
        found = analyze_source(_src("""
            class C:
                def drain(self):
                    with self._lock:
                        item = self._queue.get(block=True)
                        return self._future.result()
        """), rel=POLICY)
        assert sorted(f.symbol for f in found) == [
            "Future.result@C.drain",
            "queue-get(block=True)@C.drain"]

    def test_suppression_honored(self):
        found = analyze_source(_src("""
            import time


            class C:
                def build(self):
                    with self._build_lock:
                        # serializing the one-time build is the point
                        # kft: allow=blocking-under-lock
                        time.sleep(0.1)
        """), rel=POLICY)
        assert found == []


# Mirrors scheduler/queue.py ClusterScheduler.plan: the except path
# ends the span before re-raising.
PLAN_CLEAN = """
    from kubeflow_tpu.runtime import tracing


    class ClusterScheduler:
        def plan(self, cr_objs):
            span = tracing.start_span("scheduler.plan")
            try:
                plan = self._plan_inner(cr_objs)
            except BaseException:
                span.end(status="error")
                raise
            span.end(status="ok")
            return plan
"""

# Minimal mutation: the except path re-raises without ending the span.
PLAN_MUTATED = PLAN_CLEAN.replace(
    '            span.end(status="error")\n', "")


class TestSpanDiscipline:
    def test_real_plan_shape_is_clean(self):
        assert analyze_source(_src(PLAN_CLEAN), rel=POLICY) == []

    def test_span_leak_on_exception_edge_fires(self):
        found = analyze_source(_src(PLAN_MUTATED), rel=POLICY)
        assert [f.check for f in found] == ["span-discipline"]
        assert found[0].symbol == "leak:span@ClusterScheduler.plan"
        # Anchored at the start_span line, where the fix begins.
        assert "started here" in found[0].message

    def test_end_in_finally_is_clean(self):
        found = analyze_source(_src("""
            from kubeflow_tpu.runtime import tracing


            def handle(req):
                span = tracing.start_span("server.handle")
                try:
                    return work(req)
                finally:
                    span.end()
        """), rel=POLICY)
        assert found == []

    def test_ownership_transfer_not_a_leak(self):
        found = analyze_source(_src("""
            from kubeflow_tpu.runtime import tracing


            def begin(name):
                span = tracing.start_span(name)
                return span
        """), rel=POLICY)
        assert found == []

    def test_rebind_while_live_fires(self):
        found = analyze_source(_src("""
            from kubeflow_tpu.runtime import tracing


            def loop(items):
                for item in items:
                    span = tracing.start_span("hop")
                    work(item)
        """), rel=POLICY)
        checks = {f.symbol.split(":")[0] for f in found}
        assert "leak" in checks  # alive at exit too
        assert "rebind" in checks

    def test_hot_loop_module_must_record_span(self):
        found = analyze_source(_src("""
            from kubeflow_tpu.runtime import tracing


            def _drain(self):
                span = tracing.start_span("engine.decode")
                span.end()
        """), rel="kubeflow_tpu/serving/engine.py")
        assert [f.symbol for f in found] == ["hot-start-span"]

    def test_duplicate_span_name_fires(self):
        found = analyze_source(_src("""
            from kubeflow_tpu.runtime import tracing


            def a(ctx, t0, t1):
                tracing.record_span("batcher.queue_wait", ctx, t0, t1)


            def b(ctx, t0, t1):
                tracing.record_span("batcher.queue_wait", ctx, t0, t1)
        """), rel=POLICY)
        assert [f.symbol for f in found] == [
            "dup-name:batcher.queue_wait"]

    def test_suppression_honored(self):
        found = analyze_source(_src("""
            from kubeflow_tpu.runtime import tracing


            def fire_and_forget(name):
                # ownership handed to the store's aging sweep
                # kft: allow=span-discipline
                span = tracing.start_span(name)
                poke(span)
        """), rel=POLICY)
        assert found == []


CKPT = "kubeflow_tpu/runtime/checkpoint.py"

# Mirrors runtime/checkpoint.py _atomic_write_json.
ATOMIC_CLEAN = """
    import json
    import os


    def _atomic_write_json(path, payload):
        tmp = path.with_name(path.name + ".tmp")
        with open(tmp, "w") as f:
            json.dump(payload, f, indent=1, sort_keys=True)
            f.write("\\n")
            f.flush()
            os.fsync(f.fileno())
        os.rename(tmp, path)
"""


class TestAtomicWrite:
    def test_real_atomic_write_is_clean(self):
        assert analyze_source(_src(ATOMIC_CLEAN), rel=CKPT) == []

    def test_rename_without_fsync_fires(self):
        mutated = ATOMIC_CLEAN.replace(
            "            os.fsync(f.fileno())\n", "")
        found = analyze_source(_src(mutated), rel=CKPT)
        assert [f.check for f in found] == ["atomic-write"]
        assert found[0].symbol == \
            "rename-no-fsync:tmp@_atomic_write_json"

    def test_bare_write_of_manifest_path_fires(self):
        found = analyze_source(_src("""
            import json


            def write_manifest(path, payload):
                with open(path, "w") as f:
                    json.dump(payload, f)
        """), rel=CKPT)
        assert [f.check for f in found] == ["atomic-write"]
        assert found[0].symbol == "bare-write:path@write_manifest"

    def test_write_text_in_durable_module_fires(self):
        found = analyze_source(_src("""
            def stamp(path):
                path.write_text("done")
        """), rel="kubeflow_tpu/operator/status.py")
        assert [f.symbol for f in found] == ["bare-write-text@stamp"]

    def test_exception_path_abandoning_tmp_is_fine(self):
        # A raise between write and rename leaves only the .tmp — the
        # missing rename IS the detectable-dead-save protocol.
        mutated = ATOMIC_CLEAN.replace(
            "            f.flush()\n",
            "            maybe_raise()\n            f.flush()\n")
        assert analyze_source(_src(mutated), rel=CKPT) == []

    def test_non_durable_module_out_of_scope(self):
        found = analyze_source(_src("""
            def scratch(path):
                with open(path, "w") as f:
                    f.write("tmp")
        """), rel=POLICY)
        assert found == []

    def test_suppression_honored(self):
        found = analyze_source(_src("""
            def debug_dump(path, text):
                # scratch diagnostics, not durable state
                # kft: allow=atomic-write
                with open(path, "w") as f:
                    f.write(text)
        """), rel=CKPT)
        assert found == []


FAULTS_REL = "kubeflow_tpu/testing/faults.py"

FAULTS_DOC = '''"""Fault harness.

Hook sites planted in production code (grep for ``faults.fire``):

    engine.step       before each step-program call
    loader.load       before each load attempt
"""
'''

PRODUCER = '''"""m."""
from kubeflow_tpu.testing import faults


def go():
    faults.fire("engine.step")
    faults.fire("loader.load")
'''


class TestFaultSiteRegistry:
    def _finish(self, faults_text, producer_text, root=None):
        import ast as _ast

        from kubeflow_tpu.analysis.faultsites import FaultSiteRegistry

        checker = FaultSiteRegistry(root)
        checker.visit_module(FAULTS_REL, _ast.parse(faults_text),
                             faults_text)
        checker.visit_module("kubeflow_tpu/serving/mod.py",
                             _ast.parse(producer_text), producer_text)
        return checker.finish()

    def test_registry_and_code_in_lockstep_is_clean(self):
        assert self._finish(FAULTS_DOC, PRODUCER) == []

    def test_unregistered_site_fires(self):
        mutated = PRODUCER + '    faults.fire("engine.warp")\n'
        found = self._finish(FAULTS_DOC, mutated)
        assert [f.symbol for f in found] == ["unregistered:engine.warp"]
        assert found[0].path == "kubeflow_tpu/serving/mod.py"

    def test_phantom_registry_entry_fires(self):
        mutated = PRODUCER.replace(
            '    faults.fire("loader.load")\n', "")
        found = self._finish(FAULTS_DOC, mutated)
        assert [f.symbol for f in found] == ["phantom:loader.load"]
        assert found[0].path == FAULTS_REL
        assert found[0].line > 1  # anchored at the registry row

    def test_docs_side_checked_when_root_given(self, tmp_path):
        docs = tmp_path / "docs"
        docs.mkdir()
        (docs / "user_guide.md").write_text(
            "### 5.5 Failure semantics\n\n"
            "**Fault injection.**  Hook sites `engine.step` and\n"
            "`engine.vanished` fire scripted faults.\n\n"
            "```bash\nKFT_FAULTS=...\n```\n")
        found = self._finish(FAULTS_DOC, PRODUCER, root=tmp_path)
        assert sorted(f.symbol for f in found) == [
            "phantom-doc:engine.vanished",
            "undocumented:loader.load"]

    def test_repo_registries_in_lockstep(self):
        # The real tree: code, faults.py docstring, and user-guide
        # §5.5 must agree exactly (the full-run clean test covers
        # this too; this one isolates the checker).
        import ast as _ast

        from kubeflow_tpu.analysis.faultsites import FaultSiteRegistry

        checker = FaultSiteRegistry(REPO)
        for path in core.py_files(REPO):
            rel = path.relative_to(REPO).as_posix()
            text = path.read_text(encoding="utf-8")
            checker.visit_module(rel, _ast.parse(text), text)
        assert checker.finish() == []


class TestFingerprintStability:
    THREE = """
        import time

        A = time.time() + 1
        B = time.time() + 2
        C = time.time() + 3
    """

    def test_content_hash_disambiguates(self):
        found = analyze_source(_src(self.THREE), rel=POLICY)
        assert len(found) == 3
        fps = [f.fingerprint() for f in found]
        assert len(set(fps)) == 3
        assert all("#" in fp for fp in fps)

    def test_fixing_first_leaves_others_unchanged(self):
        before = analyze_source(_src(self.THREE), rel=POLICY)
        fixed = self.THREE.replace("        A = time.time() + 1\n", "")
        after = analyze_source(_src(fixed), rel=POLICY)
        assert len(after) == 2
        before_fps = {f.fingerprint() for f in before}
        after_fps = {f.fingerprint() for f in after}
        # The survivors keep their exact fingerprints: no renumbering,
        # no invalidated baseline entries.
        assert after_fps < before_fps

    def test_identical_lines_still_unique(self):
        found = analyze_source(_src("""
            import time


            def f():
                probe(time.time(), time.time())
        """), rel=POLICY)
        fps = [f.fingerprint() for f in found]
        assert len(fps) == 2 and len(set(fps)) == 2

    def test_singleton_keeps_bare_symbol(self):
        found = analyze_source(_src("""
            import time

            D = time.monotonic() + 1
        """), rel=POLICY)
        assert found[0].symbol == "time.monotonic@<module>"


class _DefAnchored:
    """Test-only checker anchoring findings at the ``def`` line —
    the decorated-def suppression regression needs one."""

    name = "def-anchored"

    def visit_module(self, rel, tree, text):
        import ast as _ast

        return [core.Finding(
            check="def-anchored", path=rel, line=node.lineno,
            col=node.col_offset, message="m",
            symbol=f"def:{node.name}")
            for node in _ast.walk(tree)
            if isinstance(node, (_ast.FunctionDef,
                                 _ast.AsyncFunctionDef))]

    def finish(self):
        return []


class TestDecoratedDefSuppression:
    DECORATED = """
        import functools


        # {directive}
        @functools.cache
        def f():
            return 1
    """

    def test_directive_above_decorator_covers_the_def(self):
        src = _src(self.DECORATED.format(
            directive="kft: allow=def-anchored"))
        found = analyze_source(src, rel=POLICY,
                               checkers=[_DefAnchored()])
        assert found == []

    def test_without_directive_still_fires(self):
        src = _src(self.DECORATED.format(directive="plain comment"))
        found = analyze_source(src, rel=POLICY,
                               checkers=[_DefAnchored()])
        assert [f.symbol for f in found] == ["def:f"]

    def test_directive_on_decorator_line_covers_the_def(self):
        found = analyze_source(_src("""
            import functools


            @functools.cache  # kft: allow=def-anchored
            def f():
                return 1
        """), rel=POLICY, checkers=[_DefAnchored()])
        assert found == []


def _git(root, *args):
    proc = subprocess.run(
        ["git", "-c", "user.email=t@t", "-c", "user.name=t", *args],
        cwd=str(root), capture_output=True, text=True)
    assert proc.returncode == 0, proc.stderr
    return proc.stdout


class TestChangedOnly:
    def _repo(self, tmp_path):
        pkg = tmp_path / "kubeflow_tpu" / "serving"
        pkg.mkdir(parents=True)
        (tmp_path / "ci").mkdir()
        (pkg / "a.py").write_text(
            '"""a."""\nimport time\nD = time.monotonic() + 1\n')
        (pkg / "b.py").write_text('"""b."""\n')
        _git(tmp_path, "init", "-q")
        _git(tmp_path, "add", ".")
        _git(tmp_path, "commit", "-qm", "seed")
        return pkg

    def test_only_changed_files_analyzed(self, tmp_path):
        pkg = self._repo(tmp_path)
        (pkg / "b.py").write_text(
            '"""b."""\nimport time\nE = time.monotonic() + 1\n')
        proc = _analyze(tmp_path, "--changed-only", "--base", "HEAD")
        assert proc.returncode == 1
        assert "b.py" in proc.stdout
        # a.py's pre-existing finding is out of scope for this diff.
        assert "a.py" not in proc.stdout
        full = _analyze(tmp_path)
        assert "a.py" in full.stdout and "b.py" in full.stdout

    def test_cross_module_checks_still_run_in_full(self, tmp_path):
        pkg = self._repo(tmp_path)
        (pkg / "a.py").write_text(
            '"""a."""\n'
            'C = REGISTRY.counter("kft_req_total", "h")\n'
            'C.inc(model="m")\n')
        _git(tmp_path, "add", ".")
        _git(tmp_path, "commit", "-qm", "metrics")
        # Change ONLY b.py — but its new label set conflicts with the
        # unchanged a.py registration, which the full-tree
        # cross-module pass must still see.
        (pkg / "b.py").write_text(
            '"""b."""\n'
            'REGISTRY.counter("kft_req_total", "h").inc(endpoint="e")\n')
        proc = _analyze(tmp_path, "--changed-only", "--base", "HEAD")
        assert proc.returncode == 1
        assert "one name, one label set" in proc.stdout

    def test_untouched_clean_tree_passes(self, tmp_path):
        self._repo(tmp_path)
        # a.py's violation predates the diff: a no-change run is green
        # in changed-only mode (and red in full mode).
        proc = _analyze(tmp_path, "--changed-only", "--base", "HEAD")
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert _analyze(tmp_path).returncode == 1

    def test_write_baseline_refused(self, tmp_path):
        self._repo(tmp_path)
        proc = _analyze(tmp_path, "--changed-only",
                        "--write-baseline")
        assert proc.returncode == 2
        assert "full run" in proc.stderr


# The runtime half of the lock story: the static lock-guard checker
# proves writes hold the lock; the sanitizer proves locks NEST in one
# global order (tests/conftest.py enables it for the serving/fleet
# suites under KFT_LOCKCHECK=1).
class TestLockOrderSanitizer:
    def test_inversion_closes_cycle(self):
        from kubeflow_tpu.testing import lockcheck

        sanitizer = lockcheck.install()
        try:
            sanitizer.reset()
            a = threading.Lock()
            b = threading.Lock()
            with a:
                with b:
                    pass
            with b:
                with a:
                    pass
            violations = sanitizer.violations()
            assert len(violations) == 1
            assert "closes the cycle" in repr(violations[0])
        finally:
            lockcheck.uninstall()

    def test_consistent_order_is_clean(self):
        from kubeflow_tpu.testing import lockcheck

        sanitizer = lockcheck.install()
        try:
            sanitizer.reset()
            a = threading.Lock()
            b = threading.Lock()
            for _ in range(3):
                with a:
                    with b:
                        pass
            assert sanitizer.violations() == []
        finally:
            lockcheck.uninstall()

    def test_same_site_pairs_ignored(self):
        from kubeflow_tpu.testing import lockcheck

        sanitizer = lockcheck.install()
        try:
            sanitizer.reset()
            locks = [threading.Lock() for _ in range(2)]
            with locks[0]:
                with locks[1]:
                    pass
            with locks[1]:
                with locks[0]:
                    pass
            assert sanitizer.violations() == []
        finally:
            lockcheck.uninstall()

    def test_detects_cross_thread_inversion(self):
        from kubeflow_tpu.testing import lockcheck

        sanitizer = lockcheck.install()
        try:
            sanitizer.reset()
            a = threading.Lock()
            b = threading.Lock()

            def forward():
                with a:
                    with b:
                        pass

            t = threading.Thread(target=forward)
            t.start()
            t.join()
            with b:
                with a:
                    pass
            assert len(sanitizer.violations()) == 1
        finally:
            lockcheck.uninstall()

    def test_env_gate(self, monkeypatch):
        from kubeflow_tpu.testing import lockcheck

        assert not lockcheck.enabled_in_env({})
        assert not lockcheck.enabled_in_env({"KFT_LOCKCHECK": "0"})
        assert lockcheck.enabled_in_env({"KFT_LOCKCHECK": "1"})
