"""kft-analyze: per-checker fixtures, suppressions, baseline workflow,
CLI, and the KFT_LOCKCHECK runtime lock-order sanitizer.

Each checker gets (at least) a positive fire, a negative control, and
a suppression-honored case; the baseline tests prove shrink-only
enforcement end to end through the real CLI."""

import json
import pathlib
import subprocess
import sys
import textwrap
import threading

from kubeflow_tpu.analysis import analyze_source, core
from kubeflow_tpu.analysis.clock import ClockDiscipline
from kubeflow_tpu.analysis.jitpurity import JitPurity
from kubeflow_tpu.analysis.locks import LockGuard
from kubeflow_tpu.analysis.metrics import MetricHygiene

REPO = pathlib.Path(__file__).resolve().parent.parent

POLICY = "kubeflow_tpu/serving/mod.py"


def _src(s: str) -> str:
    return '"""mod."""\n' + textwrap.dedent(s)


class TestClockDiscipline:
    def test_fires_on_policy_module(self):
        found = analyze_source(_src("""
            import time


            def drain():
                return time.monotonic() + 5
        """), rel=POLICY)
        assert [f.check for f in found] == ["clock-discipline"]
        assert "faults.monotonic" in found[0].message
        assert found[0].symbol == "time.monotonic@drain"

    def test_time_time_also_banned(self):
        found = analyze_source(_src("""
            import time

            STAMP = time.time()
        """), rel=POLICY)
        assert [f.symbol for f in found] == ["time.time@<module>"]

    def test_perf_counter_and_sleep_stay_legal(self):
        found = analyze_source(_src("""
            import time


            def measure():
                t0 = time.perf_counter()
                time.sleep(0.01)
                return time.perf_counter() - t0
        """), rel=POLICY)
        assert found == []

    def test_non_policy_module_exempt(self):
        found = analyze_source(_src("""
            import time


            def wait():
                return time.monotonic()
        """), rel="kubeflow_tpu/runtime/mod.py")
        assert found == []

    def test_same_line_suppression(self):
        found = analyze_source(_src("""
            import time

            T = time.time()  # kft: allow=clock-discipline
        """), rel=POLICY)
        assert found == []

    def test_preceding_comment_suppression(self):
        found = analyze_source(_src("""
            import time

            # wall-clock stamp leaving the process
            # kft: allow=clock-discipline
            T = time.time()
        """), rel=POLICY)
        assert found == []


LOCK_CLASS = """
    import threading


    class C:
        def __init__(self):
            self._lock = threading.Lock()
            self.x = 0

        def bump(self):
            with self._lock:
                self.x += 1
"""


class TestLockGuard:
    def test_bare_write_of_guarded_attr_fires(self):
        found = analyze_source(_src(LOCK_CLASS + """
        def reset(self):
            self.x = 0
    """), rel=POLICY)
        assert [f.check for f in found] == ["lock-guard"]
        assert "C.x" in found[0].message
        assert found[0].symbol == "C.x@reset"

    def test_locked_suffix_method_is_lock_context(self):
        found = analyze_source(_src(LOCK_CLASS + """
        def _reset_locked(self):
            self.x = 0
    """), rel=POLICY)
        assert found == []

    def test_init_writes_never_count(self):
        found = analyze_source(_src(LOCK_CLASS), rel=POLICY)
        assert found == []

    def test_unguarded_attr_writes_fine(self):
        found = analyze_source(_src(LOCK_CLASS + """
        def other(self):
            self.y = 1
    """), rel=POLICY)
        assert found == []

    def test_nested_helper_inherits_lock_state(self):
        found = analyze_source(_src("""
            import threading


            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.x = 0

                def bump(self):
                    with self._lock:
                        def helper():
                            self.x = 2
                        helper()
                        self.x += 1
        """), rel=POLICY)
        assert found == []

    def test_suppression_honored(self):
        found = analyze_source(_src(LOCK_CLASS + """
        def reset(self):
            # single-threaded by construction here
            # kft: allow=lock-guard
            self.x = 0
    """), rel=POLICY)
        assert found == []


class TestJitPurity:
    def test_partial_decorated_function_fires(self):
        found = analyze_source(_src("""
            from functools import partial

            import jax
            import time


            @partial(jax.jit, static_argnums=(0,))
            def step(n, x):
                return x + time.time()
        """), rel="kubeflow_tpu/models/mod.py")
        assert [f.check for f in found] == ["jit-purity"]
        assert "time.time" in found[0].message
        assert found[0].symbol == "time.time@step"

    def test_call_form_resolves_module_function(self):
        found = analyze_source(_src("""
            import jax
            import random


            def f(x):
                return x * random.random()


            g = jax.jit(f)
        """), rel="kubeflow_tpu/models/mod.py")
        assert [f.symbol for f in found] == ["random.random@f"]

    def test_jax_random_and_plain_functions_legal(self):
        found = analyze_source(_src("""
            import jax
            import time


            @jax.jit
            def step(x, key):
                return x + jax.random.normal(key)


            def host_loop():
                return time.perf_counter()
        """), rel="kubeflow_tpu/models/mod.py")
        assert found == []

    def test_suppression_honored(self):
        found = analyze_source(_src("""
            import jax
            import os


            @jax.jit
            def step(x):
                # kft: allow=jit-purity
                flag = os.environ.get("DEBUG")
                return x
        """), rel="kubeflow_tpu/models/mod.py")
        assert found == []


class TestMetricHygiene:
    def test_name_must_be_kft_prefixed(self):
        found = analyze_source(_src("""
            REGISTRY.counter("requests_total", "h").inc()
        """))
        assert [f.symbol for f in found] == ["name:requests_total"]

    def test_counter_must_end_total(self):
        found = analyze_source(_src("""
            REGISTRY.counter("kft_requests", "h").inc()
        """))
        assert [f.symbol for f in found] == [
            "counter-suffix:kft_requests"]

    def test_gauge_must_not_end_total(self):
        found = analyze_source(_src("""
            REGISTRY.gauge("kft_jobs_total", "h").set(1)
        """))
        assert [f.symbol for f in found] == [
            "gauge-suffix:kft_jobs_total"]

    def test_label_mismatch_across_modules(self):
        checker = MetricHygiene()
        import ast

        a = _src("""
            C = REGISTRY.counter("kft_req_total", "h")
            C.inc(model="m")
        """)
        b = _src("""
            REGISTRY.counter("kft_req_total", "h").inc(endpoint="e")
        """)
        checker.visit_module("kubeflow_tpu/a.py", ast.parse(a), a)
        checker.visit_module("kubeflow_tpu/b.py", ast.parse(b), b)
        found = checker.finish()
        assert len(found) == 1
        assert found[0].symbol.startswith("labels:kft_req_total:")
        assert "one name, one label set" in found[0].message

    def test_aggregate_plus_labeled_is_sanctioned(self):
        found = analyze_source(_src("""
            G = REGISTRY.gauge("kft_inflight", "h")
            G.set(3.0)
            G.set(1.0, model="m")
        """))
        assert found == []

    def test_constant_name_resolved(self):
        found = analyze_source(_src("""
            BAD = "kft_shed"

            REGISTRY.counter(BAD, "h").inc(model="m")
        """))
        assert [f.symbol for f in found] == ["counter-suffix:kft_shed"]

    def test_suppression_honored(self):
        found = analyze_source(_src("""
            # legacy wire name, kept for dashboard compat
            # kft: allow=metric-hygiene
            REGISTRY.counter("requests_total", "h").inc()
        """))
        assert found == []

    def test_self_attr_binding_tracked(self):
        found = analyze_source(_src("""
            class S:
                def __init__(self):
                    self._ctr = REGISTRY.counter("kft_a_total", "h")

                def hit(self):
                    self._ctr.inc(model="m")

                def miss(self):
                    self._ctr.inc(reason="r")
        """))
        assert len(found) == 1
        assert found[0].symbol.startswith("labels:kft_a_total:")


class TestBaselineAndRunner:
    def _finding(self, symbol="time.time@f"):
        return core.Finding(check="clock-discipline", path=POLICY,
                            line=3, col=0, message="m", symbol=symbol)

    def test_split_by_baseline(self):
        f_new = self._finding("new@f")
        f_old = self._finding("old@f")
        baseline = [f_old.fingerprint(), "clock-discipline::gone::x@y"]
        new, old, stale = core.split_by_baseline([f_new, f_old],
                                                 baseline)
        assert new == [f_new]
        assert old == [f_old]
        assert stale == ["clock-discipline::gone::x@y"]

    def test_dedupe_symbols_disambiguates(self):
        a, b = self._finding(), self._finding()
        out = core.dedupe_symbols([a, b])
        assert out[0].symbol == "time.time@f"
        assert out[1].symbol == "time.time@f#2"

    def test_repo_runs_clean_in_process(self):
        baseline = core.load_baseline(REPO / "ci"
                                      / "analysis_baseline.json")
        report = core.run(REPO, baseline=baseline)
        assert report.ok, [f.render() for f in report.findings] \
            + report.stale


def _scratch_repo(tmp_path, body):
    pkg = tmp_path / "kubeflow_tpu" / "serving"
    pkg.mkdir(parents=True)
    (tmp_path / "ci").mkdir()
    (pkg / "mod.py").write_text('"""mod."""\nimport time\n' + body)
    return tmp_path


def _analyze(root, *args):
    return subprocess.run(
        [sys.executable, "-m", "kubeflow_tpu.analysis",
         "--root", str(root), *args],
        capture_output=True, text=True, cwd=str(REPO))


class TestCLI:
    def test_finding_fails_run_and_renders_json(self, tmp_path):
        root = _scratch_repo(tmp_path,
                             "D = time.monotonic() + 1\n")
        proc = _analyze(root)
        assert proc.returncode == 1
        assert "clock-discipline" in proc.stdout
        proc = _analyze(root, "--json")
        assert proc.returncode == 1
        payload = json.loads(proc.stdout)
        assert payload["findings"][0]["check"] == "clock-discipline"
        assert payload["findings"][0]["path"].endswith("mod.py")

    def test_baseline_tolerates_then_shrink_only(self, tmp_path):
        root = _scratch_repo(tmp_path,
                             "D = time.monotonic() + 1\n")
        # Grandfather the finding into the baseline: run goes green.
        assert _analyze(root, "--write-baseline").returncode == 0
        proc = _analyze(root)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "1 baselined" in proc.stderr
        # Adding a NEW finding still fails — the baseline can't grow.
        mod = root / "kubeflow_tpu" / "serving" / "mod.py"
        mod.write_text(mod.read_text()
                       + "E = time.monotonic() + 2\n")
        assert _analyze(root).returncode == 1
        # Fixing the original finding makes its entry STALE: the run
        # fails until the entry is deleted (shrink-only enforcement).
        mod.write_text('"""mod."""\nimport time\n')
        proc = _analyze(root)
        assert proc.returncode == 1
        assert "stale baseline entry" in proc.stdout
        assert _analyze(root, "--write-baseline").returncode == 0
        assert _analyze(root).returncode == 0


# The runtime half of the lock story: the static lock-guard checker
# proves writes hold the lock; the sanitizer proves locks NEST in one
# global order (tests/conftest.py enables it for the serving/fleet
# suites under KFT_LOCKCHECK=1).
class TestLockOrderSanitizer:
    def test_inversion_closes_cycle(self):
        from kubeflow_tpu.testing import lockcheck

        sanitizer = lockcheck.install()
        try:
            sanitizer.reset()
            a = threading.Lock()
            b = threading.Lock()
            with a:
                with b:
                    pass
            with b:
                with a:
                    pass
            violations = sanitizer.violations()
            assert len(violations) == 1
            assert "closes the cycle" in repr(violations[0])
        finally:
            lockcheck.uninstall()

    def test_consistent_order_is_clean(self):
        from kubeflow_tpu.testing import lockcheck

        sanitizer = lockcheck.install()
        try:
            sanitizer.reset()
            a = threading.Lock()
            b = threading.Lock()
            for _ in range(3):
                with a:
                    with b:
                        pass
            assert sanitizer.violations() == []
        finally:
            lockcheck.uninstall()

    def test_same_site_pairs_ignored(self):
        from kubeflow_tpu.testing import lockcheck

        sanitizer = lockcheck.install()
        try:
            sanitizer.reset()
            locks = [threading.Lock() for _ in range(2)]
            with locks[0]:
                with locks[1]:
                    pass
            with locks[1]:
                with locks[0]:
                    pass
            assert sanitizer.violations() == []
        finally:
            lockcheck.uninstall()

    def test_detects_cross_thread_inversion(self):
        from kubeflow_tpu.testing import lockcheck

        sanitizer = lockcheck.install()
        try:
            sanitizer.reset()
            a = threading.Lock()
            b = threading.Lock()

            def forward():
                with a:
                    with b:
                        pass

            t = threading.Thread(target=forward)
            t.start()
            t.join()
            with b:
                with a:
                    pass
            assert len(sanitizer.violations()) == 1
        finally:
            lockcheck.uninstall()

    def test_env_gate(self, monkeypatch):
        from kubeflow_tpu.testing import lockcheck

        assert not lockcheck.enabled_in_env({})
        assert not lockcheck.enabled_in_env({"KFT_LOCKCHECK": "0"})
        assert lockcheck.enabled_in_env({"KFT_LOCKCHECK": "1"})
