"""runtime/profiling.py: the on-demand capture server must return the
profiler server object on success and degrade with a WARNING (never a
raise) when the port is taken or the backend lacks the profiler — it
is an observability sidecar riding in the trainer/serving process."""

import logging

import jax
import pytest

from kubeflow_tpu.runtime import profiling


class TestStartServer:
    def test_returns_profiler_server_object(self, monkeypatch):
        sentinel = object()
        calls = []

        def fake_start(port):
            calls.append(port)
            return sentinel

        monkeypatch.setattr(jax.profiler, "start_server", fake_start)
        assert profiling.start_server(9876) is sentinel
        assert calls == [9876]

    @pytest.mark.parametrize("exc", [
        RuntimeError("Address already in use"),
        NotImplementedError("profiler unavailable on this backend"),
    ])
    def test_failure_warns_and_returns_none(self, monkeypatch, caplog,
                                            exc):
        def fake_start(port):
            raise exc

        monkeypatch.setattr(jax.profiler, "start_server", fake_start)
        with caplog.at_level(logging.WARNING,
                             logger="kubeflow_tpu.runtime.profiling"):
            assert profiling.start_server(9876) is None
        assert any("unavailable" in rec.message
                   for rec in caplog.records)
