"""Golden-manifest tests.

Heir of the reference's jsonnet test tier (kubeflow/core/tests/*.jsonnet,
runner testing/test_jsonnet.py:39-62): assert exact generated objects for
each component, field-by-field rather than blob-compare, "because if you
just compare to a big blob of text its much harder to know where they
differ" (kubeflow/core/tests/jupyterhub_test.jsonnet comment).
"""

import pytest

import kubeflow_tpu.manifests  # registers prototypes  # noqa: F401
from kubeflow_tpu.config import ParamError, default_registry
from kubeflow_tpu.config.registry import App
from kubeflow_tpu.manifests import base


class TestBase:
    def test_service_headless(self):
        svc = base.service("w", "ns", {"app": "w"}, [base.port(22, "ssh")],
                           headless=True)
        assert svc["spec"]["clusterIP"] == "None"

    def test_container_drops_empty_fields(self):
        c = base.container("c", "img")
        assert set(c) == {"name", "image"}

    def test_crd_shape(self):
        obj = base.crd("tpujobs", "kubeflow-tpu.org", "TPUJob", ["v1alpha1"])
        assert obj["metadata"]["name"] == "tpujobs.kubeflow-tpu.org"
        assert obj["spec"]["versions"][0]["storage"] is True

    def test_tpu_resources_no_nvidia(self):
        res = base.tpu_resource_limits("v5e-8", 8)
        assert res == {"limits": {"google.com/tpu": 8}}

    def test_to_yaml_roundtrip(self):
        text = base.to_yaml([{"kind": "ConfigMap", "metadata": {"name": "x"}}])
        assert "kind: ConfigMap" in text or '"kind": "ConfigMap"' in text


class TestTPUJobPrototypes:
    def test_tpu_job_cr_golden(self):
        objs = default_registry.generate(
            "tpu-job", "myjob", slice_type="v5p-32", command=["python", "-m", "me"],
        )
        assert len(objs) == 1
        cr = objs[0]
        assert cr["apiVersion"] == "kubeflow-tpu.org/v1alpha1"
        assert cr["kind"] == "TPUJob"
        assert cr["metadata"] == {"name": "myjob", "namespace": "kubeflow"}
        assert cr["spec"]["sliceType"] == "v5p-32"
        assert cr["spec"]["worker"]["command"] == ["python", "-m", "me"]
        assert cr["spec"]["restartPolicy"]["maxRestarts"] == 3
        # Optional fields are omitted, not null.
        assert "storage" not in cr["spec"] and "queue" not in cr["spec"]

    def test_cnn_benchmark_args(self):
        (cr,) = default_registry.generate(
            "tpu-cnn-benchmark", "bench", model="resnet50",
            batch_size="256", num_batches=10)
        args = cr["spec"]["worker"]["args"]
        assert "--model=resnet50" in args
        assert "--batch-size-per-device=256" in args
        assert "--dtype=bfloat16" in args
        # The PS-era flags must NOT leak into the SPMD world.
        assert not any("parameter_server" in a for a in args)
        assert not any("num_ps" in a for a in args)

    def test_cnn_model_choices(self):
        with pytest.raises(ParamError):
            default_registry.generate("tpu-cnn-benchmark", "b", model="vgg99")

    def test_operator_manifests(self):
        objs = default_registry.generate("tpujob-operator", "op")
        kinds = [o["kind"] for o in objs]
        assert "CustomResourceDefinition" in kinds
        assert "Deployment" in kinds
        assert "ClusterRole" in kinds
        assert "ConfigMap" in kinds
        crd_obj = objs[kinds.index("CustomResourceDefinition")]
        assert crd_obj["metadata"]["name"] == "tpujobs.kubeflow-tpu.org"

    def test_no_nvidia_gpu_anywhere(self):
        """North-star: zero nvidia.com/gpu requests cluster-wide (BASELINE.md)."""
        import json

        app = App()
        app.add("kubeflow-core", "core")
        app.add("tpu-cnn-benchmark", "bench")
        text = json.dumps(app.render())
        assert "nvidia.com/gpu" not in text


class TestCore:
    def test_core_aggregate(self):
        objs = default_registry.generate("kubeflow-core", "core")
        kinds = [o["kind"] for o in objs]
        # hub + operator + gateway + dashboards + version configmap
        assert kinds.count("Deployment") >= 4
        assert "StatefulSet" in kinds
        names = [o["metadata"]["name"] for o in objs]
        assert "kubeflow-version" in names
        assert "ambassador" in names

    def test_telemetry_opt_in(self):
        """Usage reporting must be opt-in (reference gated on reportUsage,
        kubeflow/core/spartakus.libsonnet:4-14)."""
        import json

        off = json.dumps(default_registry.generate("kubeflow-core", "core"))
        assert "usage-telemetry" not in off
        on = json.dumps(default_registry.generate(
            "kubeflow-core", "core", report_usage=True, usage_id="u-123"))
        assert "usage-telemetry" in on and "u-123" in on

    def test_nfs_opt_in(self):
        objs = default_registry.generate("kubeflow-core", "core", disks=True)
        kinds = [o["kind"] for o in objs]
        assert "StorageClass" in kinds
        assert "PersistentVolumeClaim" in kinds
        # The hub spawner must actually use the deployed NFS StorageClass.
        hub_cm = next(o for o in objs
                      if o["kind"] == "ConfigMap"
                      and "jupyterhub_config.py" in o.get("data", {}))
        assert "user_storage_class = 'nfs'" in hub_cm["data"]["jupyterhub_config.py"]

    def test_bad_tpu_chip_count_fails_at_render(self):
        with pytest.raises(ValueError, match="chips per host"):
            base.tpu_resource_limits("v5p-32", 16)  # v5p-32 is 4 chips/host
        assert base.tpu_resource_limits("v5p-32") == \
            {"limits": {"google.com/tpu": 4}}


class TestJupyterHub:
    def test_spawner_config_golden(self):
        from kubeflow_tpu.manifests.jupyterhub import spawner_config

        cfg = spawner_config("dummy", "img:latest",
                             notebook_pvc_mount="/home/jovyan")
        assert "DummyAuthenticator" in cfg
        assert "claim-{username}" in cfg
        assert "google.com/tpu" in cfg
        assert "nvidia.com/gpu" not in cfg
        compile(cfg, "jupyterhub_config.py", "exec")  # must be valid python

    def test_iap_authenticator(self):
        from kubeflow_tpu.manifests.jupyterhub import spawner_config

        cfg = spawner_config("iap", "img:latest")
        assert "x-goog-authenticated-user-email" in cfg
        compile(cfg, "jupyterhub_config.py", "exec")

    def test_hub_manifests(self):
        objs = default_registry.generate("jupyterhub", "hub")
        by_kind = {}
        for o in objs:
            by_kind.setdefault(o["kind"], []).append(o)
        assert len(by_kind["StatefulSet"]) == 1
        # headless svc for stable DNS + LB for ingress
        svcs = by_kind["Service"]
        assert any(s["spec"].get("clusterIP") == "None" for s in svcs)
        assert any(s["spec"].get("type") == "LoadBalancer" for s in svcs)
