"""Tools tests: build_images command rendering, data stager, CLI bootstrap."""

import numpy as np
import pytest

from kubeflow_tpu.tools.build_images import (
    TARGETS,
    build_command,
    list_versions,
    load_version,
    release_workflow,
)
from kubeflow_tpu.tools.data_stager import _copy_cmd, retry, wait_job


class TestBuildImages:
    def test_commands_render_for_all_targets(self):
        config = load_version()
        for target in TARGETS:
            cmd = build_command(target, config, "reg.example/x")
            assert cmd[0] == "docker"
            assert f"reg.example/x/{target}:{config['tag_suffix']}" in cmd

    def test_version_matrix_has_multiple_entries(self):
        # Heir of the reference's per-TF-version configs
        # (components/tensorflow-notebook-image/versions/*).
        versions = list_versions()
        assert versions[0] == "default"
        assert len(versions) >= 2
        seen_tags = set()
        for version in versions:
            config = load_version(version)
            assert config["tag_suffix"] not in seen_tags
            seen_tags.add(config["tag_suffix"])
            for target in TARGETS:
                cmd = build_command(target, config, "reg.example/x")
                assert f"PYTHON_VERSION={config['python_version']}" in cmd
                assert f"JAX_VERSION={config['jax_version']}" in cmd

    def test_every_referenced_first_party_image_has_a_build_target(self):
        """Round-2 gap class: manifests/jupyterhub.py referenced a hub
        image nothing built.  Render every prototype, collect all
        first-party (ghcr.io/kubeflow-tpu/*) image references, and
        require each to have a Dockerfile + a build target in every
        version-config entry."""
        import json
        import re
        from pathlib import Path

        import kubeflow_tpu.manifests  # noqa: F401 — registers prototypes
        from kubeflow_tpu.config.registry import default_registry
        from kubeflow_tpu.tools.build_images import (
            REPO_ROOT,
            VERSIONS_DIR,
        )

        def walk(obj, found):
            if isinstance(obj, dict):
                for v in obj.values():
                    walk(v, found)
            elif isinstance(obj, list):
                for v in obj:
                    walk(v, found)
            elif isinstance(obj, str):
                for m in re.finditer(
                        r"ghcr\.io/kubeflow-tpu/([\w-]+)(?::|\b)", obj):
                    found.add(m.group(1))

        found = set()
        for proto in default_registry.names():
            try:
                walk(default_registry.generate(proto, f"x-{proto}"), found)
            except Exception:
                continue  # prototypes needing required params
        assert found, "no first-party image references rendered"
        for name in sorted(found):
            assert (REPO_ROOT / "docker" / name / "Dockerfile").exists(), (
                f"manifests reference ghcr.io/kubeflow-tpu/{name} but "
                f"docker/{name}/Dockerfile does not exist")
            for vdir in VERSIONS_DIR.iterdir():
                cfgf = vdir / "version-config.json"
                if cfgf.exists():
                    platforms = json.loads(
                        cfgf.read_text())["platforms"]
                    assert name in platforms, (
                        f"{name} missing from {cfgf}")

    def test_release_workflow_dag(self):
        wf = release_workflow("reg.example/x", load_version())
        main = [t for t in wf["spec"]["templates"]
                if t["name"] == "main"][0]
        names = {t["name"] for t in main["dag"]["tasks"]}
        assert {"checkout", "build-worker", "smoke-test"} <= names
        smoke = [t for t in main["dag"]["tasks"]
                 if t["name"] == "smoke-test"][0]
        assert set(smoke["dependencies"]) == {f"build-{t}" for t in TARGETS}


class TestDataStager:
    def test_copy_cmd_selection(self):
        assert _copy_cmd("gs://b/x", "/d")[0] == "gsutil"
        assert _copy_cmd("s3://b/x", "/d")[0] == "aws"
        assert _copy_cmd("/a", "/d")[0] == "cp"

    def test_retry_backoff(self, monkeypatch):
        sleeps = []
        monkeypatch.setattr("time.sleep", sleeps.append)
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise RuntimeError("nope")

        retry(flaky, max_attempts=5, base_delay_s=1.0)
        assert len(calls) == 3
        assert sleeps == [1.0, 2.0]

    def test_retry_exhaustion(self, monkeypatch):
        monkeypatch.setattr("time.sleep", lambda s: None)
        with pytest.raises(RuntimeError):
            retry(lambda: (_ for _ in ()).throw(RuntimeError("x")),
                  max_attempts=2)

    def test_wait_job_against_fake_control_plane(self):
        from kubeflow_tpu.operator import crd
        from kubeflow_tpu.operator.kube import FakeKube

        kube = FakeKube()
        cr = crd.TPUJobSpec(name="j", namespace="ns",
                            slice_type="v5e-1").to_custom_resource()
        cr["status"] = {"phase": "Succeeded"}
        kube.create_custom(cr)
        assert wait_job("j", "ns", kube=kube) == "Succeeded"

    def test_wait_job_timeout(self):
        from kubeflow_tpu.operator import crd
        from kubeflow_tpu.operator.kube import FakeKube

        kube = FakeKube()
        kube.create_custom(crd.TPUJobSpec(
            name="j", namespace="ns",
            slice_type="v5e-1").to_custom_resource())
        with pytest.raises(TimeoutError):
            wait_job("j", "ns", timeout_s=0.0, poll_s=0.01, kube=kube)
