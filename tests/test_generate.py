"""Decode-path tests: cached incremental decoding must agree with the
full (uncached) forward pass."""

import jax
import jax.numpy as jnp
import numpy as np

from kubeflow_tpu.models.generate import DecodeConfig, generate
from kubeflow_tpu.models.transformer import Transformer, TransformerConfig

CFG = TransformerConfig(
    vocab_size=64, d_model=32, n_layers=2, n_heads=4, n_kv_heads=2,
    d_ff=64, head_dim=8, max_seq_len=64, dtype=jnp.float32,
)


def setup():
    model = Transformer(CFG)
    prompt = jnp.asarray(
        np.random.RandomState(0).randint(1, CFG.vocab_size, (2, 8)),
        jnp.int32)
    variables = model.init(jax.random.key(0), prompt)
    return model, variables["params"], prompt


def test_greedy_decode_consistent_with_full_forward():
    model, params, prompt = setup()
    tokens, _ = generate(CFG, params, prompt,
                         DecodeConfig(max_new_tokens=6))
    assert tokens.shape == (2, 14)
    # Re-run the whole sequence densely: every generated token must be the
    # argmax of the dense logits at its position.
    dense = model.apply({"params": params}, tokens)
    for pos in range(8, 14):
        expected = jnp.argmax(dense[:, pos - 1], axis=-1)
        np.testing.assert_array_equal(
            np.asarray(tokens[:, pos]), np.asarray(expected))


def test_prefill_logits_match_dense():
    model, params, prompt = setup()
    from kubeflow_tpu.models.generate import (
        _forward_with_cache,
        init_cache,
    )

    cache = init_cache(CFG, 2, 8)
    logits, _ = _forward_with_cache(CFG, params, prompt, cache, 0)
    dense = model.apply({"params": params}, prompt)
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(dense), atol=2e-4
    )


def test_eos_stops_sampling():
    model, params, prompt = setup()
    # Force eos = whatever greedy produces first; the following tokens
    # must be 0 (the pad the decode loop emits after done).
    tokens, _ = generate(CFG, params, prompt,
                         DecodeConfig(max_new_tokens=4))
    first = int(tokens[0, 8])
    tokens2, _ = generate(
        CFG, params, prompt,
        DecodeConfig(max_new_tokens=4, eos_token=first))
    assert int(tokens2[0, 9]) == 0


def test_temperature_sampling_runs():
    model, params, prompt = setup()
    tokens, _ = generate(CFG, params, prompt,
                         DecodeConfig(max_new_tokens=3, temperature=1.0),
                         rng=jax.random.key(7))
    assert tokens.shape == (2, 11)


def test_top_k_one_equals_greedy():
    model, params, prompt = setup()
    greedy, _ = generate(CFG, params, prompt,
                         DecodeConfig(max_new_tokens=5))
    topk1, _ = generate(
        CFG, params, prompt,
        DecodeConfig(max_new_tokens=5, temperature=0.7, top_k=1),
        rng=jax.random.key(3))
    np.testing.assert_array_equal(np.asarray(greedy), np.asarray(topk1))


def test_top_k_samples_stay_in_top_set():
    model, params, prompt = setup()
    k = 3
    # One decode step at high temperature: the sampled token must be one
    # of the top-k next-token candidates of the prefill logits.
    _, prefill_logits = generate(
        CFG, params, prompt, DecodeConfig(max_new_tokens=1))
    del prefill_logits  # logits returned are post-sample; recompute:
    model2 = Transformer(CFG)
    full = model2.apply({"params": params}, prompt)
    allowed = np.asarray(
        jax.lax.top_k(full[:, -1], k)[1])           # [b, k] token ids
    for seed in range(5):
        toks, _ = generate(
            CFG, params, prompt,
            DecodeConfig(max_new_tokens=1, temperature=2.0, top_k=k),
            rng=jax.random.key(seed))
        first_new = np.asarray(toks[:, prompt.shape[1]])
        for b in range(prompt.shape[0]):
            assert first_new[b] in allowed[b], (first_new, allowed)


def test_top_p_tiny_equals_greedy():
    # p smaller than any single token's probability keeps only the
    # argmax -> nucleus sampling degenerates to greedy.
    model, params, prompt = setup()
    greedy, _ = generate(CFG, params, prompt,
                         DecodeConfig(max_new_tokens=5))
    nucleus, _ = generate(
        CFG, params, prompt,
        DecodeConfig(max_new_tokens=5, temperature=1.0, top_p=1e-9),
        rng=jax.random.key(11))
    np.testing.assert_array_equal(np.asarray(greedy), np.asarray(nucleus))


def test_top_p_one_matches_plain_sampling():
    model, params, prompt = setup()
    plain, _ = generate(
        CFG, params, prompt,
        DecodeConfig(max_new_tokens=4, temperature=1.0),
        rng=jax.random.key(5))
    nucleus, _ = generate(
        CFG, params, prompt,
        DecodeConfig(max_new_tokens=4, temperature=1.0, top_p=1.0),
        rng=jax.random.key(5))
    np.testing.assert_array_equal(np.asarray(plain), np.asarray(nucleus))


def test_invalid_top_p_rejected():
    import pytest

    with pytest.raises(ValueError, match="top_p"):
        DecodeConfig(top_p=0.0)
    with pytest.raises(ValueError, match="top_k"):
        DecodeConfig(top_k=-1)


def test_top_k_larger_than_vocab_is_no_filter():
    model, params, prompt = setup()
    plain, _ = generate(
        CFG, params, prompt,
        DecodeConfig(max_new_tokens=3, temperature=1.0),
        rng=jax.random.key(9))
    big_k, _ = generate(
        CFG, params, prompt,
        DecodeConfig(max_new_tokens=3, temperature=1.0, top_k=10_000),
        rng=jax.random.key(9))
    np.testing.assert_array_equal(np.asarray(plain), np.asarray(big_k))


class TestLeftPaddedDecode:
    """Bucketed mixed-length decode: a LEFT-padded row with prompt_len
    must produce exactly the tokens it would alone at natural length
    (pad keys masked, rope offset by the pad) — the contract
    serving/model_server.py BucketedLMBatcher depends on."""

    def test_padded_row_matches_unpadded(self):
        _, params, _ = setup()
        rng = np.random.RandomState(3)
        short = jnp.asarray(rng.randint(1, CFG.vocab_size, (1, 5)),
                            jnp.int32)
        long = jnp.asarray(rng.randint(1, CFG.vocab_size, (1, 8)),
                           jnp.int32)
        dc = DecodeConfig(max_new_tokens=6)
        ref_short, _ = generate(CFG, params, short, dc)
        ref_long, _ = generate(CFG, params, long, dc)

        # One bucketed batch of 8: short row left-padded by 3.
        padded_short = jnp.concatenate(
            [jnp.zeros((1, 3), jnp.int32), short], axis=1)
        batch = jnp.concatenate([padded_short, long], axis=0)
        plen = jnp.asarray([5, 8], jnp.int32)
        out, _ = generate(CFG, params, batch, dc, prompt_len=plen)
        # Short row: strip the 3 pad columns, then compare end to end.
        np.testing.assert_array_equal(
            np.asarray(out[0, 3:]), np.asarray(ref_short[0]))
        np.testing.assert_array_equal(
            np.asarray(out[1]), np.asarray(ref_long[0]))

    def test_full_length_prompt_len_is_identity(self):
        _, params, prompt = setup()
        dc = DecodeConfig(max_new_tokens=4)
        ref, _ = generate(CFG, params, prompt, dc)
        out, _ = generate(CFG, params, prompt, dc,
                          prompt_len=jnp.asarray([8, 8], jnp.int32))
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_bucketed_batcher_mixed_lengths_share_batches():
    """Mixed-length prompts coalesce through BucketedLMBatcher and come
    back at their natural shapes with per-length-correct decodes."""
    from kubeflow_tpu.serving.model_server import BucketedLMBatcher

    _, params, _ = setup()
    dc = DecodeConfig(max_new_tokens=4)
    rng = np.random.RandomState(7)
    # Lengths straddle the [8, 16] bucket boundary: dispatch-time
    # promotion pads a batch containing the length-10 prompt to bucket
    # 16, so even cross-bucket mixes share device batches (the
    # submit-time-padding design re-split them and measured ~5x below
    # uniform-length req/s on-chip).
    prompts = [rng.randint(1, CFG.vocab_size, (1, n)).astype(np.int32)
               for n in (3, 5, 10, 8)]
    refs = [np.asarray(generate(CFG, params, jnp.asarray(p), dc)[0])
            for p in prompts]

    def predict(inputs):
        out, _ = generate(
            CFG, params, jnp.asarray(inputs["tokens"], jnp.int32), dc,
            prompt_len=jnp.asarray(inputs["prompt_len"], jnp.int32))
        return {"tokens": out}

    mb = BucketedLMBatcher(
        predict, buckets=[8, 16], max_batch_size=4,
        batch_timeout_s=0.05, allowed_batch_sizes=[1, 2, 4], name="lmb")
    try:
        import concurrent.futures as cf

        with cf.ThreadPoolExecutor(4) as ex:
            outs = list(ex.map(
                lambda p: mb.submit({"tokens": p}), prompts))
        for p, out, ref in zip(prompts, outs, refs):
            assert out["tokens"].shape == (1, p.shape[1] + 4)
            np.testing.assert_array_equal(out["tokens"], ref)
        # One shared queue: with 4 concurrent clients at a generous
        # timeout the mixed-bucket prompts coalesce rather than running
        # batch-1 (the pre-bucketing behavior) or splitting per bucket
        # (the submit-time-padding behavior).
        stats = mb.stats()
        assert stats["mean_batch_size"] > 1.0, stats
    finally:
        mb.close()


def test_bucketed_batcher_promotion_is_bounded():
    """max_promotion_factor (VERDICT r4 item 7): a short prompt must
    never be co-batched into a bucket more than factor x its own — the
    per-decode-step KV span is set by the batch bucket, so unbounded
    promotion makes a 128-token request pay a 4096-token attention span
    per step on a wide length spread."""
    from kubeflow_tpu.serving.model_server import BucketedLMBatcher

    widths = []

    def predict(inputs):
        widths.append(np.asarray(inputs["tokens"]).shape[1])
        return {"tokens": np.asarray(inputs["tokens"])}

    mb = BucketedLMBatcher(
        predict, buckets=[32, 128, 512, 4096], max_batch_size=2,
        batch_timeout_s=0.05, allowed_batch_sizes=[1, 2], name="lmb4")
    try:
        import concurrent.futures as cf

        with cf.ThreadPoolExecutor(2) as ex:
            short = ex.submit(
                mb.submit, {"tokens": np.ones((1, 100), np.int32)})
            long = ex.submit(
                mb.submit, {"tokens": np.ones((1, 3000), np.int32)})
            short, long = short.result(), long.result()
        # Separate bands (128 vs 4096 with factor 4) -> separate
        # dispatches: the short prompt padded to ITS band's bucket.
        assert sorted(widths) == [128, 4096], widths
        assert short["tokens"].shape == (1, 100)
        assert long["tokens"].shape == (1, 3000)
        assert mb.stats()["batches"] == 2
    finally:
        mb.close()


def test_bucketed_batcher_unbounded_promotion_shares_one_queue():
    """max_promotion_factor=None restores the single shared queue: the
    same spread promotes the short prompt to the long one's bucket."""
    from kubeflow_tpu.serving.model_server import BucketedLMBatcher

    widths = []

    def predict(inputs):
        widths.append(np.asarray(inputs["tokens"]).shape[1])
        return {"tokens": np.asarray(inputs["tokens"])}

    mb = BucketedLMBatcher(
        predict, buckets=[32, 128, 512, 4096],
        max_promotion_factor=None, max_batch_size=2,
        batch_timeout_s=0.2, allowed_batch_sizes=[1, 2], name="lmb5")
    try:
        import concurrent.futures as cf

        with cf.ThreadPoolExecutor(2) as ex:
            outs = list(ex.map(
                lambda n: mb.submit({"tokens": np.ones((1, n), np.int32)}),
                [100, 3000]))
        assert widths == [4096], widths  # one co-batched dispatch
        assert outs[0]["tokens"].shape == (1, 100)
    finally:
        mb.close()


def test_bucketed_batcher_oversize_prompt_rejected():
    from kubeflow_tpu.serving.model_server import BucketedLMBatcher

    mb = BucketedLMBatcher(lambda i: i, buckets=[8], name="lmb2")
    try:
        import pytest

        with pytest.raises(ValueError, match="exceeds"):
            mb.submit({"tokens": np.zeros((1, 9), np.int32)})
    finally:
        mb.close()


def test_bucketed_batcher_rejects_multi_row_submit():
    from kubeflow_tpu.serving.model_server import BucketedLMBatcher

    mb = BucketedLMBatcher(lambda i: i, buckets=[8], name="lmb3")
    try:
        import pytest

        with pytest.raises(ValueError, match="one prompt"):
            mb.submit({"tokens": np.zeros((2, 5), np.int32)})
    finally:
        mb.close()


def test_flash_prefill_matches_dot_decode():
    """A flash-configured model's generate() (flash prefill, cached dot
    decode) must produce exactly the dot-configured model's tokens.
    This suite runs on the CPU fake slice (conftest pins the platform)
    where flash falls back to the XLA path, so exact equality pins the
    GATE logic and shapes; flash-kernel-vs-dot numerics are pinned
    separately with tolerances in tests/test_ops.py."""
    _, params, prompt = setup()
    dc = DecodeConfig(max_new_tokens=5)
    ref, _ = generate(CFG, params, prompt, dc)
    cfg_flash = TransformerConfig(
        **{**CFG.__dict__, "attention": "flash"})
    out, _ = generate(cfg_flash, params, prompt, dc)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))

    # Left-padded rows ride flash prefill via the kernel's per-row
    # key-start mask (CPU fallback applies the same mask in the dot
    # path) and must decode identically to the unpadded reference;
    # int8 caches keep the dot path (goldens pin that rounding) and
    # must still decode at the right shape.
    padded = jnp.concatenate(
        [jnp.zeros((2, 3), jnp.int32), prompt], axis=1)
    out_pad, _ = generate(cfg_flash, params, padded, dc,
                          prompt_len=jnp.asarray([8, 8], jnp.int32))
    np.testing.assert_array_equal(np.asarray(out_pad[:, 3:]),
                                  np.asarray(ref))
    out_q, _ = generate(
        TransformerConfig(**{**CFG.__dict__, "attention": "flash"}),
        params, prompt,
        DecodeConfig(max_new_tokens=5, kv_cache_dtype="int8"))
    assert out_q.shape == ref.shape


def test_eos_while_loop_matches_scan_when_eos_never_fires():
    """eos_token >= 0 switches decode to the early-exit while_loop; when
    no row ever emits EOS it must produce exactly the fixed-length scan's
    tokens (the early exit changes wall time, never content)."""
    _, params, prompt = setup()
    ref, _ = generate(CFG, params, prompt, DecodeConfig(max_new_tokens=6))
    used = set(np.asarray(ref[:, prompt.shape[1]:]).ravel().tolist())
    eos = next(i for i in range(CFG.vocab_size) if i not in used)
    out, _ = generate(CFG, params, prompt,
                      DecodeConfig(max_new_tokens=6, eos_token=eos))
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_eos_early_exit_payoff_case_matches_scan_semantics():
    """The case the while_loop exists FOR: every row done well before
    max_new_tokens.  Tokens must equal the fixed-length run truncated at
    EOS (EOS emitted, zeros after), at the full output shape."""
    _, params, prompt = setup()
    row = prompt[:1]  # single row: its first greedy token becomes EOS
    ref, _ = generate(CFG, params, row, DecodeConfig(max_new_tokens=6))
    t = row.shape[1]
    eos = int(ref[0, t])
    out, _ = generate(CFG, params, row,
                      DecodeConfig(max_new_tokens=6, eos_token=eos))
    assert out.shape == ref.shape
    expect = np.asarray(ref).copy()
    expect[0, t + 1:] = 0  # everything after the EOS emission pads to 0
    np.testing.assert_array_equal(np.asarray(out), expect)
