"""Multi-chip serving: tensor-parallel engine identity + KV handoff.

The conftest forces an 8-device CPU host platform, so meshes of 2 and
4 build hermetically.  The battery the multichip item demands:

  - the sharded engine (params + paged KV pool placed over a
    ``tensor`` mesh, serving/sharding.py) is BIT-IDENTICAL to the
    single-device engine for greedy decode — across plain prompts,
    prefix-cache hits, int8 KV pools, and speculative verify;
  - disaggregated handoff (prefill replica exports finished block
    pages, decode replica imports them) equals local prefill at EVERY
    page-coverage cut, i.e. every chunk boundary the import can land
    on;
  - the partition-rule machinery degrades gracefully (non-divisible
    dims replicate, rank mismatches replicate, bad --mesh specs fail
    fast).
"""

import numpy as np
import pytest

SEED = 20260804
VOCAB, NEW_TOKENS = 96, 10


@pytest.fixture(scope="module")
def lm():
    """Tiny LM whose head/kv-head/mlp/vocab dims divide 4, so mesh 2
    AND mesh 4 shard every rule'd dim; yields (cfg, params, decode,
    reference) with reference(prompt) -> full greedy token list."""
    import jax
    from flax import linen as nn

    from kubeflow_tpu.models.generate import DecodeConfig, generate
    from kubeflow_tpu.models.transformer import Transformer
    from kubeflow_tpu.serving.loaders import _model_config

    cfg = _model_config({
        "vocab_size": VOCAB, "d_model": 32, "n_layers": 2,
        "n_heads": 4, "n_kv_heads": 4, "d_ff": 64, "head_dim": 8,
        "max_seq_len": 64, "dtype": "float32"})
    model = Transformer(cfg)
    params = nn.unbox(model.init(
        jax.random.key(SEED), np.zeros((1, 8), np.int32))["params"])
    decode = DecodeConfig(max_new_tokens=NEW_TOKENS, temperature=0.0)
    cache = {}

    def reference(prompt):
        key = np.asarray(prompt, np.int32).tobytes()
        if key not in cache:
            out, _ = generate(cfg, params,
                              np.asarray(prompt, np.int32)[None],
                              decode)
            cache[key] = np.asarray(out)[0].tolist()
        return cache[key]

    return cfg, params, decode, reference


def _prompts():
    rng = np.random.RandomState(SEED)
    return [rng.randint(1, VOCAB, size=(n,)).astype(np.int32)
            for n in (8, 5, 11, 16)]


def _engine(lm, **kw):
    from kubeflow_tpu.serving.engine import DecodeEngine

    cfg, params, decode, _ = lm
    kw.setdefault("slots", 2)
    kw.setdefault("prefill_len", 16)
    kw.setdefault("prefill_chunk_tokens", 4)
    kw.setdefault("kv_block_tokens", 4)
    return DecodeEngine(cfg, params, decode, **kw)


def _mesh(n):
    from kubeflow_tpu.serving import sharding

    return sharding.build_mesh({"tensor": n})


class TestPartitionRules:
    def test_parse_mesh_flag(self):
        from kubeflow_tpu.serving import sharding

        assert sharding.parse_mesh_flag("") == {}
        assert sharding.parse_mesh_flag("tensor=4") == {"tensor": 4}
        with pytest.raises(ValueError, match="axis=N"):
            sharding.parse_mesh_flag("tensor")
        with pytest.raises(ValueError, match="unknown serving mesh"):
            sharding.parse_mesh_flag("fsdp=2")
        with pytest.raises(ValueError, match="not an integer"):
            sharding.parse_mesh_flag("tensor=x")
        with pytest.raises(ValueError, match=">= 1"):
            sharding.parse_mesh_flag("tensor=0")

    def test_build_mesh_sizes(self):
        from kubeflow_tpu.serving import sharding

        assert sharding.build_mesh({}) is None
        assert sharding.build_mesh({"tensor": 1}) is None
        mesh = sharding.build_mesh({"tensor": 4})
        assert mesh is not None and mesh.devices.size == 4
        assert sharding.mesh_devices(mesh) == 4
        assert sharding.mesh_devices(None) == 1
        with pytest.raises(ValueError, match="exceeds"):
            sharding.build_mesh({"tensor": 999})

    def test_rules_map_param_tree(self, lm):
        from jax.sharding import PartitionSpec

        from kubeflow_tpu.serving import sharding

        cfg, params, _, _ = lm
        specs = sharding.match_partition_rules(
            sharding.LM_PARTITION_RULES, params)
        assert specs["layers"]["attn"]["wq"] \
            == PartitionSpec(None, None, "tensor", None)
        assert specs["layers"]["mlp"]["wo"] \
            == PartitionSpec(None, "tensor", None)
        assert specs["embed"] == PartitionSpec("tensor", None)
        # Norm scales fall through to the replicate catch-all.
        assert specs["final_norm"]["scale"] == PartitionSpec()

    def test_non_divisible_dim_degrades_to_replicated(self, lm):
        import jax

        from kubeflow_tpu.serving import sharding

        cfg, params, _, _ = lm
        mesh = _mesh(4)
        # 3 kv-heads do not divide tensor=4: the wkv rule must
        # replicate that dim instead of crashing construction.
        odd = {"layers": {"attn": {
            "wkv": np.zeros((2, 2, 32, 3, 8), np.float32)}}}
        placed = sharding.shard_params(odd, mesh)
        leaf = placed["layers"]["attn"]["wkv"]
        assert leaf.sharding.spec == jax.sharding.PartitionSpec(
            None, None, None, None, None)

    def test_rank_mismatch_replicates(self):
        from jax.sharding import PartitionSpec

        from kubeflow_tpu.serving import sharding

        # A QTensor scale companion rides its values rule at a lower
        # rank: the guard must replicate, not raise.
        tree = {"layers": {"attn": {"wq": np.zeros((4,), np.float32)}}}
        specs = sharding.match_partition_rules(
            sharding.LM_PARTITION_RULES, tree)
        assert specs["layers"]["attn"]["wq"] == PartitionSpec()


class TestShardedEngineIdentity:
    @pytest.mark.parametrize("tensor", [2, 4])
    def test_greedy_identity_and_prefix_hits(self, lm, tensor):
        """Sharded engine == generate() for mixed-length greedy
        prompts, slot reuse included; then a shared-prefix admission
        aliases cached pages and stays identical."""
        _, _, _, reference = lm
        eng = _engine(lm, mesh=_mesh(tensor), name=f"mesh{tensor}")
        try:
            for p in _prompts():
                got = eng.submit({"tokens": p})["tokens"][0].tolist()
                assert got == reference(p), (
                    f"mesh={tensor} diverged for len {p.shape[0]}")
            # Prefix hit: shares the 8-token (2-page) prefix of the
            # 11-token prompt just published.
            p = _prompts()[2]
            out = eng.submit({"tokens": p, "return_timing": True})
            assert out["tokens"][0].tolist() == reference(p)
            assert out["cached_tokens"] == 8
            stats = eng.stats()
            assert stats["mesh_devices"] == tensor
            assert stats["prefix_hits"] >= 1
        finally:
            eng.close()

    def test_int8_kv_identity(self, lm):
        """Sharded int8 pool == single-device int8 pool, token for
        token (int8 tokens may differ from fp tokens — the comparison
        is sharded-vs-single at the SAME quantization)."""
        import dataclasses

        cfg, params, decode, _ = lm
        decode8 = dataclasses.replace(decode, kv_cache_dtype="int8")
        lm8 = (cfg, params, decode8, None)
        single = _engine(lm8, name="int8-single")
        shard = _engine(lm8, mesh=_mesh(2), name="int8-mesh2")
        try:
            for p in _prompts():
                want = single.submit({"tokens": p})["tokens"][0]
                got = shard.submit({"tokens": p})["tokens"][0]
                assert got.tolist() == want.tolist(), (
                    f"int8 sharded diverged for len {p.shape[0]}")
        finally:
            single.close()
            shard.close()

    def test_speculative_identity(self, lm):
        """Sharded speculative verify == generate(): the verify
        program compiles SPMD like the others and exact-match
        acceptance keeps greedy identity."""
        _, _, _, reference = lm
        eng = _engine(lm, mesh=_mesh(2), speculative_tokens=4,
                      name="spec-mesh2")
        try:
            # Repetitive prompt the n-gram drafter can predict, plus a
            # random one (mixed batch, draft_len 0 rider).
            rep = np.asarray([7, 9, 7, 9, 7, 9, 7, 9], np.int32)
            rand = _prompts()[0]
            for p in (rep, rand, rep):
                got = eng.submit({"tokens": p})["tokens"][0].tolist()
                assert got == reference(p)
            assert eng.stats()["spec_steps"] >= 0  # battery sanity
        finally:
            eng.close()

    def test_mesh_gauge_zeroed_on_close(self, lm):
        from kubeflow_tpu.runtime.prom import (
            REGISTRY,
            parse_metrics,
            sample_value,
        )

        eng = _engine(lm, mesh=_mesh(2), name="gauge-mesh")
        parsed = parse_metrics(REGISTRY.render())
        assert sample_value(parsed, "kft_engine_mesh_devices",
                            engine="gauge-mesh") == 2
        eng.close()
        parsed = parse_metrics(REGISTRY.render())
        assert sample_value(parsed, "kft_engine_mesh_devices",
                            engine="gauge-mesh") == 0


class TestKVHandoff:
    def test_import_identity_at_every_chunk_boundary(self, lm):
        """Export once, then import trimmed to EVERY page-coverage cut
        (1..max pages): each lands the resumed chunk schedule at a
        different boundary, and every one must equal the local run."""
        _, _, _, reference = lm
        pre = _engine(lm, name="ho-pre")
        p = _prompts()[3]  # 16 tokens, bt=4 -> up to 3 full pages
        try:
            out = pre.prefill_export({"tokens": p})
            ho = out["kv_handoff"]
            assert ho["tokens_covered"] == 12
            assert ho["k"].shape[1] == 3
            max_pages = ho["k"].shape[1]
            for n in range(1, max_pages + 1):
                cut = {"block_tokens": ho["block_tokens"],
                       "tokens_covered": n * ho["block_tokens"],
                       "k": ho["k"][:, :n], "v": ho["v"][:, :n]}
                dec = _engine(lm, prefix_caching=False,
                              name=f"ho-dec{n}")
                try:
                    got = dec.submit({"tokens": p, "kv_handoff": cut})
                    assert got["tokens"][0].tolist() == reference(p), (
                        f"handoff diverged at {n}-page coverage")
                    stats = dec.stats()
                    assert stats["handoff_pages_in"] == n
                    assert dec.compiled_programs()["kv_import"] == 1
                finally:
                    dec.close()
            assert pre.stats()["handoff_pages_out"] == max_pages
        finally:
            pre.close()

    def test_import_into_sharded_engine(self, lm):
        """Cross-tier AND cross-layout: a single-device prefill
        replica's pages import into a mesh-2 decode replica."""
        _, _, _, reference = lm
        pre = _engine(lm, name="ho-pre-s")
        dec = _engine(lm, mesh=_mesh(2), name="ho-dec-s")
        p = _prompts()[2]
        try:
            ho = pre.prefill_export({"tokens": p})["kv_handoff"]
            got = dec.submit({"tokens": p, "kv_handoff": ho})
            assert got["tokens"][0].tolist() == reference(p)
        finally:
            pre.close()
            dec.close()

    def test_int8_handoff_roundtrip(self, lm):
        import dataclasses

        cfg, params, decode, _ = lm
        lm8 = (cfg, params,
               dataclasses.replace(decode, kv_cache_dtype="int8"),
               None)
        pre = _engine(lm8, name="ho8-pre")
        dec = _engine(lm8, name="ho8-dec")
        ctl = _engine(lm8, name="ho8-ctl")
        p = _prompts()[2]
        try:
            want = ctl.submit({"tokens": p})["tokens"][0].tolist()
            ho = pre.prefill_export({"tokens": p})["kv_handoff"]
            assert isinstance(ho["k"], dict)  # values + scale
            got = dec.submit({"tokens": p, "kv_handoff": ho})
            assert got["tokens"][0].tolist() == want
        finally:
            pre.close()
            dec.close()
            ctl.close()

    def test_geometry_and_dtype_mismatches_are_typed(self, lm):
        pre = _engine(lm, name="ho-err-pre")
        dec = _engine(lm, kv_block_tokens=8, name="ho-err-dec")
        p = _prompts()[3]
        try:
            ho = pre.prefill_export({"tokens": p})["kv_handoff"]
            with pytest.raises(ValueError, match="block_tokens"):
                dec.submit({"tokens": p, "kv_handoff": ho})
            with pytest.raises(ValueError, match="quantized"):
                pre.submit({"tokens": p, "kv_handoff": {
                    "block_tokens": 4,
                    "k": {"values": np.zeros((2, 1, 4, 4, 8), np.int8),
                          "scale": np.zeros((2, 1, 4, 4), np.float32)},
                    "v": {"values": np.zeros((2, 1, 4, 4, 8), np.int8),
                          "scale": np.zeros((2, 1, 4, 4),
                                            np.float32)}}})
            with pytest.raises(ValueError, match="pages"):
                pre.submit({"tokens": p, "kv_handoff": {
                    "block_tokens": 4,
                    "k": np.zeros((2, 1, 4, 9, 8), np.float32),
                    "v": np.zeros((2, 1, 4, 9, 8), np.float32)}})
        finally:
            pre.close()
            dec.close()

    def test_short_prompt_exports_nothing(self, lm):
        """A prompt under one full page (limit = len - 1) has no
        exportable pages: the payload is absent and the caller falls
        back to the untiered path."""
        pre = _engine(lm, name="ho-short")
        try:
            out = pre.prefill_export(
                {"tokens": np.asarray([3, 5, 9], np.int32)})
            assert "kv_handoff" not in out
        finally:
            pre.close()

    def test_wire_codec_roundtrip(self, lm):
        from kubeflow_tpu.serving.http import (
            decode_kv_handoff,
            encode_kv_handoff,
        )

        pre = _engine(lm, name="ho-wire")
        p = _prompts()[3]
        try:
            ho = pre.prefill_export({"tokens": p})["kv_handoff"]
            wire = encode_kv_handoff(ho)
            assert isinstance(wire["k"]["b64"], str)
            back = decode_kv_handoff(wire)
            np.testing.assert_array_equal(back["k"], ho["k"])
            np.testing.assert_array_equal(back["v"], ho["v"])
            assert back["block_tokens"] == ho["block_tokens"]
            with pytest.raises(ValueError):
                decode_kv_handoff({"block_tokens": 4, "k": "junk",
                                   "v": "junk"})
        finally:
            pre.close()

    def test_handoff_fault_site_fires(self, lm):
        from kubeflow_tpu.testing import faults

        pre = _engine(lm, name="ho-fault")
        p = _prompts()[3]
        try:
            inj = faults.parse("engine.kv_handoff:raise")
            faults.install(inj)
            try:
                with pytest.raises(Exception):
                    pre.prefill_export({"tokens": p})
            finally:
                faults.install(None)
            assert inj.fired("engine.kv_handoff") >= 1
        finally:
            pre.close()
