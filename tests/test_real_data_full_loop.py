"""Data-plane full loop on REAL text: corpus -> shards -> train (with
checkpoints) -> restore -> export -> serve -> decoded text.

The control-plane twin is tests/test_e2e_full_loop.py; this one chains
every data-side subsystem end to end the way a user would: the corpus
tool ingests this repo's own documentation (real prose), the training
ENTRYPOINT (tools/train_lm) streams the shards through the native
loader and writes orbax checkpoints, the checkpoint restores into an
export for the lm_generate serving loader, and the served model decodes
tokens that round-trip through the tokenizer back to text.  The
reference's heritage claim ("always ran real models end-to-end") is
matched at data-plane level by this chain.
"""

import json
import os
import pathlib
import subprocess
import sys

import numpy as np

REPO = pathlib.Path(__file__).parents[1]

MODEL = dict(vocab_size=258, d_model=32, n_layers=2, n_heads=4,
             n_kv_heads=4, d_ff=64, head_dim=8, max_seq_len=96)


def test_corpus_train_checkpoint_export_serve_decode(tmp_path):
    from kubeflow_tpu.tools import corpus

    # 1. Corpus: this repo's own README + user guide — real text that
    # ships with the source tree (byte tokenizer: exact round-trip).
    out = tmp_path / "corpus"
    rc = corpus.main([
        "--source", str(REPO / "README.md"),
        str(REPO / "docs" / "user_guide.md"),
        "--tokenizer", "byte", "--seq-len", "64", "--out", str(out),
    ])
    assert rc == 0
    shards = sorted(str(p) for p in out.glob("corpus-*.kftr"))
    assert shards
    meta = json.loads((out / "corpus.json").read_text())
    assert meta["vocab_size"] == 258

    # 2. Train through the DEPLOYED entrypoint with checkpointing on —
    # a separate OS process, like the TPUJob container would run it.
    ckpt_dir = tmp_path / "ckpts"
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=str(REPO),
               XLA_FLAGS="--xla_force_host_platform_device_count=2")
    proc = subprocess.run(
        [sys.executable, "-m", "kubeflow_tpu.tools.train_lm",
         "--d-model", "32", "--n-layers", "2", "--n-heads", "4",
         "--n-kv-heads", "4", "--d-ff", "64", "--head-dim", "8",
         "--vocab-size", "258", "--seq-len", "64",
         "--batch-size-per-device", "1", "--steps", "4",
         "--checkpoint-dir", str(ckpt_dir), "--checkpoint-every", "2",
         "--log-every", "2", "--metrics-out", str(tmp_path / "m.json"),
         "--data-files", *shards],
        capture_output=True, text=True, timeout=600, env=env, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    hist = json.loads((tmp_path / "m.json").read_text())["history"]
    assert hist and all(np.isfinite(h["loss"]) for h in hist)

    # 3. Restore through the Trainer's own resume path (the state the
    # entrypoint checkpointed is the full TrainState), then export.
    import jax
    import optax

    from kubeflow_tpu.models.transformer import lm_task
    from kubeflow_tpu.parallel import MeshSpec
    from kubeflow_tpu.runtime.checkpoint import CheckpointManager
    from kubeflow_tpu.runtime.metrics import MetricsLogger
    from kubeflow_tpu.serving.export import export
    from kubeflow_tpu.serving.loaders import _model_config
    from kubeflow_tpu.serving.model_server import ModelServer

    cfg = _model_config(dict(MODEL, dtype="float32"))
    mesh = MeshSpec(data=2).build(jax.devices()[:2])  # trainer topology
    init_fn, loss_fn = lm_task(cfg, mesh=mesh)
    with CheckpointManager(str(ckpt_dir)) as mgr:
        assert mgr.latest_step() is not None and mgr.latest_step() >= 3
        from kubeflow_tpu.runtime.train import Trainer

        with open(os.devnull, "w") as devnull:
            trainer = Trainer(
                init_fn=init_fn, loss_fn=loss_fn, tx=optax.adamw(1e-3),
                mesh=mesh, metrics=MetricsLogger(stream=devnull),
            )
            state, resumed_step = mgr.restore_or_init(
                trainer.create_state())
        assert resumed_step >= 3
        params = jax.tree_util.tree_map(np.asarray, state.params)

    export(str(tmp_path / "served"), 1, {"params": params},
           loader="kubeflow_tpu.serving.loaders:lm_generate",
           config={"model": dict(MODEL, dtype="float32"),
                   "max_new_tokens": 8, "temperature": 0.0})

    # 4. Serve and decode REAL text: tokenize a prompt from the corpus
    # source, generate, and round-trip the completion back to a string.
    server = ModelServer()
    server.add_model("lm", str(tmp_path / "served"))
    tok = corpus.load_tokenizer(str(out / "tokenizer.json"))
    prompt_ids = tok.encode_ids("kubeflow")
    result = server.predict(
        "lm", {"tokens": np.asarray([prompt_ids], np.int32)})
    tokens = np.asarray(result["tokens"])
    assert tokens.shape == (1, len(prompt_ids) + 8)
    # Prompt is echoed verbatim ahead of the completion.
    np.testing.assert_array_equal(tokens[0, :len(prompt_ids)],
                                  prompt_ids)
    text = tok.decode(tokens[0].tolist())
    assert text.startswith("kubeflow")
