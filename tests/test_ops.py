"""Attention op tests: XLA reference vs Pallas flash kernel (interpreter)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_tpu.ops.attention import dot_product_attention
from kubeflow_tpu.ops.flash import flash_attention


def rand_qkv(rng, b=2, s=64, h=2, hkv=None, d=16, dtype=jnp.float32):
    hkv = hkv or h
    q = jnp.asarray(rng.randn(b, s, h, d), dtype)
    k = jnp.asarray(rng.randn(b, s, hkv, d), dtype)
    v = jnp.asarray(rng.randn(b, s, hkv, d), dtype)
    return q, k, v


class TestDotProductAttention:
    def test_causal_masks_future(self):
        rng = np.random.RandomState(0)
        q, k, v = rand_qkv(rng, s=8)
        out1 = dot_product_attention(q, k, v, causal=True)
        # Perturb the last key/value: outputs at positions < 7 unchanged.
        k2 = k.at[:, -1].set(0.0)
        v2 = v.at[:, -1].set(0.0)
        out2 = dot_product_attention(q, k2, v2, causal=True)
        np.testing.assert_allclose(
            np.asarray(out1[:, :-1]), np.asarray(out2[:, :-1]), atol=1e-6
        )

    def test_matches_manual_softmax(self):
        rng = np.random.RandomState(1)
        q, k, v = rand_qkv(rng, b=1, s=4, h=1, d=8)
        out = dot_product_attention(q, k, v, causal=False)
        scores = np.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(8)
        w = jax.nn.softmax(jnp.asarray(scores), axis=-1)
        ref = np.einsum("bhqk,bkhd->bqhd", np.asarray(w), v)
        np.testing.assert_allclose(np.asarray(out), ref, atol=1e-5)

    def test_gqa_equals_repeated_kv(self):
        rng = np.random.RandomState(2)
        q, k, v = rand_qkv(rng, h=4, hkv=2)
        out_gqa = dot_product_attention(q, k, v)
        out_rep = dot_product_attention(
            q, jnp.repeat(k, 2, axis=2), jnp.repeat(v, 2, axis=2)
        )
        np.testing.assert_allclose(
            np.asarray(out_gqa), np.asarray(out_rep), atol=1e-6
        )

    def test_segment_mask_blocks_cross_segment(self):
        rng = np.random.RandomState(3)
        q, k, v = rand_qkv(rng, b=1, s=8, h=1, d=8)
        segs = jnp.asarray([[0, 0, 0, 0, 1, 1, 1, 1]])
        out = dot_product_attention(q, k, v, causal=False, segment_ids=segs)
        # Second segment must be independent of first-segment k/v.
        k2 = k.at[:, :4].set(0.0)
        v2 = v.at[:, :4].set(0.0)
        out2 = dot_product_attention(q, k2, v2, causal=False, segment_ids=segs)
        np.testing.assert_allclose(
            np.asarray(out[:, 4:]), np.asarray(out2[:, 4:]), atol=1e-6
        )


class TestFlashKernel:
    """Kernel logic via the Pallas interpreter (no TPU needed)."""

    @pytest.mark.parametrize("causal", [True, False])
    @pytest.mark.parametrize("s,block", [(64, 16), (128, 128), (96, 32)])
    def test_matches_reference(self, causal, s, block):
        rng = np.random.RandomState(4)
        q, k, v = rand_qkv(rng, b=1, s=s, h=2, d=32)
        ref = dot_product_attention(q, k, v, causal=causal)
        out = flash_attention(
            q, k, v, causal=causal, block_q=block, block_k=block,
            interpret=True,
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5
        )

    def test_gqa(self):
        rng = np.random.RandomState(5)
        q, k, v = rand_qkv(rng, s=32, h=4, hkv=2, d=16)
        ref = dot_product_attention(q, k, v, causal=True)
        out = flash_attention(q, k, v, causal=True, block_q=16, block_k=16,
                              interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_gradients_flow(self):
        rng = np.random.RandomState(6)
        q, k, v = rand_qkv(rng, b=1, s=16, h=1, d=8)

        def loss_flash(q, k, v):
            return flash_attention(q, k, v, block_q=8, block_k=8,
                                   interpret=True).sum()

        def loss_ref(q, k, v):
            return dot_product_attention(q, k, v).sum()

        g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)

    def test_cpu_fallback_without_interpret(self):
        rng = np.random.RandomState(7)
        q, k, v = rand_qkv(rng, s=16)
        ref = dot_product_attention(q, k, v)
        out = flash_attention(q, k, v)  # backend=cpu -> XLA fallback
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-6)


class TestTwoPassFlash:
    """Splash-style two-pass causal forward: full blocks + fine diagonal
    band merged in log space (ops/flash.py _flash_fwd_two_pass)."""

    @pytest.mark.parametrize("s,bq,bk,bd", [
        (128, 32, 64, 16),   # several full blocks + band
        (128, 32, 32, 8),    # bq == bk
        (96, 32, 32, 16),    # non-power-of-two sequence
        (256, 64, 128, 32),  # wide k blocks (the production shape, scaled)
    ])
    def test_matches_reference(self, s, bq, bk, bd):
        rng = np.random.RandomState(11)
        q, k, v = rand_qkv(rng, b=1, s=s, h=2, d=32)
        ref = dot_product_attention(q, k, v, causal=True)
        out = flash_attention(
            q, k, v, causal=True, block_q=bq, block_k=bk, block_diag=bd,
            interpret=True,
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_pure_band_when_no_full_blocks(self):
        """sq <= block_k leaves pass A with zero full blocks; the
        internal two-pass path must degrade to the band-only pass."""
        from kubeflow_tpu.ops.flash import _flash_fwd_two_pass, _to_bhsd

        rng = np.random.RandomState(12)
        q, k, v = rand_qkv(rng, b=1, s=64, h=1, d=16)
        ref = dot_product_attention(q, k, v, causal=True)
        o, lse = _flash_fwd_two_pass(
            _to_bhsd(q), _to_bhsd(k), _to_bhsd(v),
            block_q=64, block_k=64, block_diag=16, interpret=True)
        np.testing.assert_allclose(
            np.asarray(o.reshape(1, 1, 64, 16).transpose(0, 2, 1, 3)),
            np.asarray(ref), atol=2e-5)

    def test_lse_matches_manual(self):
        """The merged lse must be the TRUE full-softmax lse — it feeds
        the unchanged backward kernels."""
        from kubeflow_tpu.ops.flash import _flash_fwd_two_pass, _to_bhsd

        rng = np.random.RandomState(13)
        q, k, v = rand_qkv(rng, b=1, s=128, h=1, d=16)
        _, lse = _flash_fwd_two_pass(
            _to_bhsd(q), _to_bhsd(k), _to_bhsd(v),
            block_q=32, block_k=64, block_diag=16, interpret=True)
        s_full = np.einsum(
            "bqhd,bkhd->bhqk", np.asarray(q, np.float32),
            np.asarray(k, np.float32)) * (16 ** -0.5)
        mask = np.tril(np.ones((128, 128), bool))
        s_full = np.where(mask[None, None], s_full, -np.inf)
        manual = np.log(np.exp(
            s_full - s_full.max(-1, keepdims=True)).sum(-1)) \
            + s_full.max(-1)
        np.testing.assert_allclose(
            np.asarray(lse).reshape(1, 1, 128), manual, atol=2e-5)

    def test_gradients_match_reference(self):
        rng = np.random.RandomState(14)
        q, k, v = rand_qkv(rng, b=1, s=128, h=2, d=16)

        def loss_two_pass(q, k, v):
            return (flash_attention(
                q, k, v, causal=True, block_q=32, block_k=64,
                block_diag=16, interpret=True) ** 2).sum()

        def loss_ref(q, k, v):
            return (dot_product_attention(q, k, v, causal=True) ** 2).sum()

        g1 = jax.grad(loss_two_pass, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=5e-5)

    def test_dispatch_requires_self_attention_shape(self):
        """block_diag on a cross-attention shape (sq != sk) silently
        uses the classic single pass — same result either way."""
        rng = np.random.RandomState(15)
        q, _, _ = rand_qkv(rng, b=1, s=32, h=1, d=16)
        _, k, v = rand_qkv(rng, b=1, s=64, h=1, d=16)
        out = flash_attention(
            q, k, v, causal=False, block_q=16, block_k=16,
            block_diag=8, interpret=True)
        ref = dot_product_attention(q, k, v, causal=False)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5)


class TestFlashBackwardKernels:
    """The Pallas blockwise backward (dq and dkv passes) via interpreter."""

    @pytest.mark.parametrize("causal", [True, False])
    @pytest.mark.parametrize("s,block", [(64, 16), (96, 32)])
    def test_grads_match_reference(self, causal, s, block):
        rng = np.random.RandomState(8)
        q, k, v = rand_qkv(rng, b=2, s=s, h=2, d=32)

        def loss_flash(q, k, v):
            out = flash_attention(q, k, v, causal=causal, block_q=block,
                                  block_k=block, interpret=True)
            return (out * out).sum()  # non-uniform cotangent

        def loss_ref(q, k, v):
            out = dot_product_attention(q, k, v, causal=causal)
            return (out * out).sum()

        g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=5e-4, rtol=1e-3
            )

    def test_gqa_grads_fold_head_groups(self):
        rng = np.random.RandomState(9)
        q, k, v = rand_qkv(rng, b=1, s=32, h=4, hkv=2, d=16)

        def loss(fn):
            def inner(q, k, v):
                return fn(q, k, v).sum()
            return inner

        flash = lambda q, k, v: flash_attention(
            q, k, v, causal=True, block_q=16, block_k=16, interpret=True)
        ref = lambda q, k, v: dot_product_attention(q, k, v, causal=True)
        g1 = jax.grad(loss(flash), argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(loss(ref), argnums=(0, 1, 2))(q, k, v)
        assert g1[1].shape == k.shape  # folded back to kv head count
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=5e-4, rtol=1e-3)

    def test_lse_matches_manual(self):
        from kubeflow_tpu.ops.flash import flash_fwd_with_lse

        rng = np.random.RandomState(10)
        q, k, v = rand_qkv(rng, b=1, s=32, h=2, d=16)
        o, lse = flash_fwd_with_lse(q, k, v, causal=False, block_q=16,
                                    block_k=16, interpret=True)
        scores = np.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(16)
        ref_lse = jax.nn.logsumexp(jnp.asarray(scores), axis=-1)
        np.testing.assert_allclose(np.asarray(lse), np.asarray(ref_lse),
                                   atol=1e-5)
        ref_o = dot_product_attention(q, k, v, causal=False)
        np.testing.assert_allclose(np.asarray(o), np.asarray(ref_o),
                                   atol=2e-5)


class TestFlashRematResiduals:
    """The flash fwd names its (out, lse) residuals (checkpoint_name) so a
    remat policy can keep them instead of re-running the forward kernel
    inside the backward pass — the policy composition models/transformer.py
    installs when save_attn_residuals is set."""

    def _policy(self):
        return jax.checkpoint_policies.save_from_both_policies(
            jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
            jax.checkpoint_policies.save_only_these_names(
                "flash_out", "flash_lse"),
        )

    def test_grads_identical_with_saved_residuals(self):
        rng = np.random.RandomState(11)
        q, k, v = rand_qkv(rng, b=2, s=64, h=2, d=32)

        def attend(q, k, v):
            out = flash_attention(q, k, v, causal=True, block_q=16,
                                  block_k=16, interpret=True)
            return (out * out).sum()

        plain = jax.grad(attend, argnums=(0, 1, 2))(q, k, v)
        saved = jax.grad(
            jax.checkpoint(attend, policy=self._policy())
        , argnums=(0, 1, 2))(q, k, v)
        recomputed = jax.grad(
            jax.checkpoint(
                attend,
                policy=jax.checkpoint_policies
                .dots_with_no_batch_dims_saveable,
            ), argnums=(0, 1, 2))(q, k, v)
        for a, b, c in zip(plain, saved, recomputed):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-6)
            np.testing.assert_allclose(np.asarray(a), np.asarray(c),
                                       atol=1e-6)

    def test_policy_elides_fwd_recompute(self):
        """With the residuals saved, the backward jaxpr must not contain a
        second forward kernel call (the lse-producing pallas call)."""
        rng = np.random.RandomState(12)
        q, k, v = rand_qkv(rng, b=1, s=32, h=2, d=16)

        def attend(q, k, v):
            out = flash_attention(q, k, v, causal=True, block_q=16,
                                  block_k=16, interpret=True)
            return (out * out).sum()

        def n_pallas_calls(policy):
            fn = jax.checkpoint(attend, policy=policy) if policy else attend
            jaxpr = jax.make_jaxpr(
                jax.grad(fn, argnums=(0, 1, 2)))(q, k, v)
            return str(jaxpr).count("pallas_call")

        # Ungated grad: fwd + dq + dkv = 3 kernel launches.  Saving the
        # named residuals keeps it at 3 under remat; dropping them forces
        # a 4th launch (the fwd recompute inside the backward).
        assert n_pallas_calls(None) == 3
        assert n_pallas_calls(self._policy()) == 3
        assert n_pallas_calls(
            jax.checkpoint_policies.dots_with_no_batch_dims_saveable) == 4


class TestFlashKeyStartMask:
    """Forward-only per-row key-start mask (left-padded decode prefill):
    the kernel's early k blocks are the masked ones, which stresses the
    online-softmax sentinel handling (a fully-masked running max must
    not turn exp(sentinel - sentinel) into weight 1)."""

    def _ref(self, q, k, v, start):
        return dot_product_attention(
            q, k, v, causal=True, kv_valid_start=start)

    @pytest.mark.parametrize("block", [32, 64])
    def test_masked_matches_reference(self, block):
        rng = np.random.RandomState(11)
        b, s, h, d = 3, 128, 2, 16
        q, k, v = (jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
                   for _ in range(3))
        # Row 0 unpadded; row 1 pad crosses a block boundary; row 2 pad
        # larger than a whole k block (the sentinel-corruption case).
        start = jnp.asarray([0, block // 2 + 3, block + 7], jnp.int32)
        out = flash_attention(
            q, k, v, causal=True, block_q=block, block_k=block,
            interpret=True, kv_valid_start=start)
        ref = self._ref(q, k, v, start)
        # Pad-row queries (pos < start) are fully masked: the kernel
        # emits zeros there, the reference emits uniform-weight noise —
        # both are garbage no caller reads.  Compare valid rows only.
        for row in range(b):
            s0 = int(start[row])
            np.testing.assert_allclose(
                np.asarray(out[row, s0:]), np.asarray(ref[row, s0:]),
                atol=2e-5, rtol=2e-5)

    def test_fully_masked_rows_are_finite(self):
        rng = np.random.RandomState(12)
        q, k, v = (jnp.asarray(rng.randn(1, 64, 2, 16), jnp.float32)
                   for _ in range(3))
        out = flash_attention(
            q, k, v, causal=True, block_q=32, block_k=32,
            interpret=True, kv_valid_start=jnp.asarray([40], jnp.int32))
        assert np.isfinite(np.asarray(out)).all()
        # Pad-row outputs are exactly zero (l == 0 guard).
        np.testing.assert_array_equal(
            np.asarray(out[0, :32]), np.zeros_like(out[0, :32]))
