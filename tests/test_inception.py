"""Inception-v3 structural tests (small spatial input to keep CPU cost sane)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_tpu.models.inception import InceptionV3


@pytest.mark.slow  # ~26s inception compile on CPU
def test_forward_shapes_and_dtype():
    model = InceptionV3(num_classes=10)
    x = jnp.zeros((1, 96, 96, 3))
    variables = model.init(jax.random.key(0), x, train=False)
    out = model.apply(variables, x, train=False)
    assert out.shape == (1, 10)
    assert out.dtype == jnp.float32


@pytest.mark.slow  # ~14s inception compile on CPU
def test_train_mode_updates_batch_stats():
    model = InceptionV3(num_classes=4)
    x = jnp.asarray(np.random.RandomState(0).randn(2, 96, 96, 3), jnp.float32)
    variables = model.init(jax.random.key(0), x, train=False)
    _, updated = model.apply(
        variables, x, train=True, mutable=["batch_stats"],
        rngs={"dropout": jax.random.key(1)},
    )
    before = jax.tree_util.tree_leaves(variables["batch_stats"])[0]
    after = jax.tree_util.tree_leaves(updated["batch_stats"])[0]
    assert not np.allclose(np.asarray(before), np.asarray(after))
