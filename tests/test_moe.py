"""MoE layer + expert-parallel transformer tests."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from flax import linen as nn

from kubeflow_tpu.models.moe import MoEMLP
from kubeflow_tpu.models.transformer import TransformerConfig, lm_task
from kubeflow_tpu.parallel import EXPERT, MeshSpec
from kubeflow_tpu.runtime.metrics import MetricsLogger
from kubeflow_tpu.runtime.train import Trainer


class TestMoELayer:
    def test_shapes_and_aux(self):
        layer = MoEMLP(d_model=16, d_ff=32, num_experts=4,
                       capacity_factor=2.0)
        x = jnp.asarray(np.random.RandomState(0).randn(2, 8, 16),
                        jnp.bfloat16)
        variables = layer.init(jax.random.key(0), x)
        out, sown = layer.apply(variables, x, mutable=["losses"])
        assert out.shape == (2, 8, 16)
        aux = jax.tree_util.tree_leaves(sown["losses"])[0]
        # Switch aux loss is >= 1 (equality at perfectly uniform routing).
        assert float(aux) >= 0.99

    def test_expert_params_annotated(self):
        layer = MoEMLP(d_model=16, d_ff=32, num_experts=4)
        x = jnp.zeros((1, 4, 16), jnp.bfloat16)
        variables = layer.init(jax.random.key(0), x)
        wi = variables["params"]["wi"]
        assert wi.names[0] == "expert"

    def test_gather_impl_matches_einsum(self):
        """Same routing decisions, two materializations: the slot-index
        gather path must reproduce the one-hot einsum path exactly
        (same drops, same gates) — it replaces an O(g*E*C*d)
        contraction with O(E*C*d) row moves, not different math."""
        rng = np.random.RandomState(4)
        x = jnp.asarray(rng.randn(2, 32, 16), jnp.bfloat16)
        outs, grads = {}, {}
        for impl in ("einsum", "gather"):
            layer = MoEMLP(d_model=16, d_ff=32, num_experts=4,
                           capacity_factor=1.0, group_size=16, impl=impl)
            variables = layer.init(jax.random.key(0), x)

            def loss(v, impl=impl, layer=layer):
                out, _ = layer.apply(v, x, mutable=["losses"])
                return jnp.sum(out.astype(jnp.float32) ** 2), out

            (l, out), g = jax.value_and_grad(loss, has_aux=True)(variables)
            outs[impl] = np.asarray(out, np.float32)
            grads[impl] = g
        np.testing.assert_allclose(outs["einsum"], outs["gather"],
                                   atol=2e-2, rtol=1e-2)
        for a, b in zip(jax.tree_util.tree_leaves(grads["einsum"]),
                        jax.tree_util.tree_leaves(grads["gather"])):
            np.testing.assert_allclose(
                np.asarray(a, np.float32), np.asarray(b, np.float32),
                atol=5e-2, rtol=5e-2)

    def test_capacity_drops_dont_nan(self):
        # Tiny capacity: most tokens dropped; output must stay finite.
        layer = MoEMLP(d_model=8, d_ff=16, num_experts=2,
                       capacity_factor=0.1)
        x = jnp.asarray(np.random.RandomState(1).randn(1, 32, 8),
                        jnp.bfloat16)
        variables = layer.init(jax.random.key(0), x)
        out, _ = layer.apply(variables, x, mutable=["losses"])
        assert np.isfinite(np.asarray(out, np.float32)).all()


class TestMoETransformer:
    CFG = TransformerConfig(
        vocab_size=64, d_model=32, n_layers=2, n_heads=4, n_kv_heads=4,
        d_ff=64, head_dim=8, max_seq_len=32, moe_experts=4,
    )

    @pytest.mark.slow  # ~13s; layer-level MoE tests above keep the coverage
    def test_train_on_expert_parallel_mesh(self, devices):
        mesh = MeshSpec(data=2, expert=2, tensor=2).build(devices)
        init_fn, loss_fn = lm_task(self.CFG)
        tr = Trainer(
            init_fn=init_fn, loss_fn=loss_fn, tx=optax.adam(3e-3),
            mesh=mesh, metrics=MetricsLogger(stream=open("/dev/null", "w")),
        )
        state = tr.create_state()
        # Expert dim of wi [layers, E, 2, d, f] sharded over `expert`.
        wi = state.params["layers"]["moe"]["wi"]
        assert EXPERT in tuple(wi.sharding.spec), wi.sharding.spec

        rng = np.random.RandomState(0)

        def data():
            while True:
                start = rng.randint(0, 8, size=(8, 1))
                toks = (start + np.arange(16)[None, :]) % 16
                yield {"tokens": toks.astype(np.int32)}

        state = tr.fit(data(), num_steps=10, examples_per_step=8,
                       log_every=0)
        assert np.isfinite(tr._last_metrics["loss"])
        assert "moe_aux" in tr._last_metrics


class TestGroupFit:
    def test_odd_token_count_gets_largest_divisor_group(self):
        # 2 x 33 = 66 tokens, group_size 16 -> largest divisor 11 (a gcd
        # shortcut would give 2, collapsing capacity to top_k).
        layer = MoEMLP(d_model=8, d_ff=16, num_experts=2, group_size=16)
        x = jnp.asarray(np.random.RandomState(2).randn(2, 33, 8),
                        jnp.bfloat16)
        variables = layer.init(jax.random.key(0), x)
        out, _ = layer.apply(variables, x, mutable=["losses"])
        assert out.shape == (2, 33, 8)
        assert np.isfinite(np.asarray(out, np.float32)).all()
        # The routing tensors pin the fitted group: [G, g] = [6, 11]
        # regardless of dispatch implementation (the einsum path also
        # carries a [6, 11, 2, C] one-hot; the gather path does not).
        jaxpr = str(jax.make_jaxpr(
            lambda v, x: layer.apply(v, x, mutable=["losses"]))(
                variables, x))
        assert "i32[6,11]" in jaxpr, "expected 6 groups of 11 tokens"
