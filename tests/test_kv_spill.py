"""Hierarchical KV survivability (§5.10): host-RAM spill tier under
the paged pool, shed-free degradation, and resume-by-fetch failover.

The acceptance battery the robustness item demands:

  - pool pressure SPILLS idle records instead of destroy-evicting
    them, so no pool-exhaustion shed (and no content loss) happens
    while spillable mass exists — regression-tested;
  - a parked multi-turn session whose device pages were spilled
    resumes through the kv_import re-import path BIT-IDENTICAL to an
    uninterrupted control, with TTFT ≪ the cold prefill of the same
    context (re-import replaces chunked prefill compute);
  - the b64 wire codec makes host-tier pages portable: a failover
    survivor imports a corpse's peer-fetched pages (:fetch_kv) and
    continues the stream bit-identically;
  - the `engine.spill` fault at spill-in re-import sheds a typed 429
    with no page leaked in either tier; `engine.fetch` faults surface
    to the router's recompute fallback.
"""

import numpy as np
import pytest

SEED = 20260807
VOCAB, NEW_TOKENS = 96, 10


@pytest.fixture(scope="module")
def lm():
    """Tiny LM, (cfg, params, decode, reference) with
    reference(prompt) -> full greedy token list (prompt + emitted)."""
    import jax
    from flax import linen as nn

    from kubeflow_tpu.models.generate import DecodeConfig, generate
    from kubeflow_tpu.models.transformer import Transformer
    from kubeflow_tpu.serving.loaders import _model_config

    cfg = _model_config({
        "vocab_size": VOCAB, "d_model": 32, "n_layers": 2,
        "n_heads": 4, "n_kv_heads": 2, "d_ff": 64, "head_dim": 8,
        "max_seq_len": 64, "dtype": "float32"})
    model = Transformer(cfg)
    params = nn.unbox(model.init(
        jax.random.key(SEED), np.zeros((1, 8), np.int32))["params"])
    decode = DecodeConfig(max_new_tokens=NEW_TOKENS, temperature=0.0)
    cache = {}

    def reference(prompt):
        key = np.asarray(prompt, np.int32).tobytes()
        if key not in cache:
            out, _ = generate(cfg, params,
                              np.asarray(prompt, np.int32)[None],
                              decode)
            cache[key] = np.asarray(out)[0].tolist()
        return cache[key]

    return cfg, params, decode, reference


def _engine(lm, **kw):
    from kubeflow_tpu.serving.engine import DecodeEngine

    cfg, params, decode, _ = lm
    kw.setdefault("slots", 2)
    kw.setdefault("prefill_len", 32)
    kw.setdefault("prefill_chunk_tokens", 8)
    kw.setdefault("kv_block_tokens", 4)
    return DecodeEngine(cfg, params, decode, **kw)


def _prompt(n, lo=1):
    rng = np.random.RandomState(SEED + n)
    return rng.randint(lo, VOCAB, size=(n,)).astype(np.int32)


class TestSpillTier:
    def test_pressure_spills_never_sheds_and_resume_is_identical(
            self, lm):
        """The tentpole end-to-end, engine level: a tight device pool
        (12 pages) accumulates parked sessions well past its own
        capacity; pool pressure evacuates the LRU records to the host
        tier (spills, NOT destructive evictions, NOT sheds), and each
        parked session's second turn re-imports its spilled pages and
        emits greedy tokens bit-identical to the uninterrupted
        reference."""
        _, _, _, reference = lm
        eng = _engine(lm, kv_pool_blocks=12, host_spill_blocks=48,
                      name="spill-core")
        try:
            sessions = []
            for i in range(5):
                p = _prompt(9 + i)
                out = eng.submit({"tokens": p, "park_kv": True})
                turn1 = out["tokens"][0].tolist()
                assert turn1 == reference(p)
                sessions.append((p, turn1))
            st = eng.stats()
            mgr = eng._mgr.stats()
            # 5 parked contexts x 4+ full pages each cannot all be
            # device-resident in a 12-page pool: the overflow MUST
            # have spilled, and nothing may have shed or been
            # destroyed while the host tier had room.
            assert st["shed"] == 0
            assert st["kv_spill_pages_out"] > 0
            assert st["parked_sessions"] == 5
            assert mgr["evictions"] == 0, (
                "destructive eviction while spillable mass existed")
            assert mgr["block_evictions"] == 0
            assert st["host_tier_used"] > 0
            assert st["kv_spill_ratio"] > 0
            assert st["tokens_addressable"] == (12 + 48) * 4
            eng._mgr.check_invariants()
            # Turn 2 on every session, oldest first — the oldest are
            # the certainly-spilled ones.
            for p, turn1 in sessions:
                turn2 = np.concatenate(
                    [np.asarray(turn1, np.int32), _prompt(3, lo=90)])
                got = eng.submit({"tokens": turn2})
                assert got["tokens"][0].tolist() == \
                    reference(turn2.tolist()), "resumed turn diverged"
            st = eng.stats()
            assert st["kv_spill_pages_in"] > 0, (
                "no session resumed through the re-import path")
            assert st["shed"] == 0
            assert eng._mgr.stats()["evictions"] == 0
            assert eng.compiled_programs()["kv_import"] == 1
            eng._mgr.check_invariants()
        finally:
            eng.close()

    def test_reimport_skips_prefill_compute(self, lm):
        """TTFT mechanism check (CPU-sim stands in for wall clock,
        PR-13 precedent): resuming a spilled session must run FEWER
        prefill chunks than the cold prefill of the same context —
        the imported pages replace that compute entirely."""
        eng = _engine(lm, kv_pool_blocks=10, host_spill_blocks=32,
                      name="spill-ttft")
        cold = _engine(lm, kv_pool_blocks=32, name="spill-cold")
        try:
            p = _prompt(16)
            out = eng.submit({"tokens": p, "park_kv": True})
            ctx = out["tokens"][0].tolist()  # 26 tokens
            chunks_before = eng.stats()["prefill_chunks"]
            # Force the resume through the HOST tier: drop the device
            # records (the test's stand-in for churn having spilled
            # them — the core test above covers natural pressure).
            with eng._lock:
                while eng._mgr._lru:
                    _, rec = eng._mgr._lru.popitem(last=False)
                    eng._mgr._drop_record(rec, count=False)
            got = eng.submit({"tokens": np.asarray(ctx, np.int32)})
            warm_chunks = eng.stats()["prefill_chunks"] - chunks_before
            cold.submit({"tokens": np.asarray(ctx, np.int32)})
            cold_chunks = cold.stats()["prefill_chunks"]
            assert eng.stats()["kv_spill_pages_in"] > 0
            assert warm_chunks < cold_chunks, (
                f"re-import ran {warm_chunks} prefill chunks vs "
                f"{cold_chunks} cold — no TTFT win")
            assert got["tokens"][0].tolist() == \
                cold.submit({"tokens": np.asarray(ctx, np.int32)}
                            )["tokens"][0].tolist()
        finally:
            eng.close()
            cold.close()

    def test_spill_in_fault_sheds_typed_429_with_no_leak(self, lm):
        """A spill-gather fault mid-admission (the re-import leg) must
        shed the request as a typed Overloaded — never crash the loop,
        never leak a page in either tier — and the SAME request must
        succeed once the fault clears (proof the host record survived
        the shed)."""
        from kubeflow_tpu.serving.errors import Overloaded
        from kubeflow_tpu.testing import faults

        _, _, _, reference = lm
        eng = _engine(lm, kv_pool_blocks=10, host_spill_blocks=32,
                      name="spill-fault")
        try:
            p = _prompt(16)
            ctx = eng.submit({"tokens": p, "park_kv": True}
                             )["tokens"][0].tolist()
            with eng._lock:
                while eng._mgr._lru:
                    _, rec = eng._mgr._lru.popitem(last=False)
                    eng._mgr._drop_record(rec, count=False)
            host_before = eng._mgr.host_used_blocks()
            used_before = eng._mgr.used_blocks()
            inj = faults.parse("engine.spill:raise")
            faults.install(inj)
            try:
                with pytest.raises(Overloaded):
                    eng.submit({"tokens": np.asarray(ctx, np.int32)})
            finally:
                faults.install(None)
            assert inj.fired("engine.spill") >= 1
            st = eng.stats()
            assert st["shed"] == 1
            assert eng._mgr.used_blocks() == used_before, (
                "device pages leaked by the shed path")
            assert eng._mgr.host_used_blocks() == host_before, (
                "host pages destroyed by the shed path")
            eng._mgr.check_invariants()
            # Fault cleared: the identical request now re-imports and
            # matches the reference — nothing was corrupted.
            got = eng.submit({"tokens": np.asarray(ctx, np.int32)})
            assert got["tokens"][0].tolist() == reference(ctx)
            assert eng.stats()["kv_spill_pages_in"] > 0
        finally:
            eng.close()

    def test_spill_out_fault_is_graceful(self, lm):
        """A fault at the spill-OUT gather abandons that spill (the
        record stays device-resident, destroy-eviction remains the
        fallback) — traffic keeps flowing, nothing sheds."""
        from kubeflow_tpu.testing import faults

        _, _, _, reference = lm
        eng = _engine(lm, kv_pool_blocks=12, host_spill_blocks=48,
                      name="spill-out-fault")
        try:
            inj = faults.parse("engine.spill:raise")
            faults.install(inj)
            try:
                for i in range(4):
                    p = _prompt(10 + i)
                    got = eng.submit({"tokens": p, "park_kv": True})
                    assert got["tokens"][0].tolist() == reference(p)
            finally:
                faults.install(None)
            st = eng.stats()
            assert st["shed"] == 0
            assert st["kv_spill_pages_out"] == 0  # every spill faulted
            eng._mgr.check_invariants()
        finally:
            eng.close()


class TestFetchResume:
    def test_fetch_payload_resumes_on_a_peer_bit_identical(self, lm):
        """Resume-by-fetch, engine level: replica A parks a session;
        a survivor B (cold cache) imports A's :fetch_kv payload —
        round-tripped through the b64 wire codec, as the router ships
        it — plus resume_tokens, and emits exactly the suffix an
        uninterrupted run would have."""
        from kubeflow_tpu.serving.http import (
            decode_kv_handoff,
            encode_kv_handoff,
        )

        _, _, _, reference = lm
        a = _engine(lm, kv_pool_blocks=16, host_spill_blocks=32,
                    name="fetch-a")
        b = _engine(lm, kv_pool_blocks=16, host_spill_blocks=32,
                    name="fetch-b")
        try:
            p = _prompt(12)
            a.submit({"tokens": p, "park_kv": True})
            want = reference(p)
            # Mid-generation death after 4 delivered tokens: the
            # router replays on B with prompt + delivered and the
            # payload it fetched from A.
            delivered = want[len(p):len(p) + 4]
            context = np.asarray(list(p) + delivered, np.int32)
            fetched = a.fetch_kv({"tokens": context})
            assert fetched["tokens_covered"] > 0
            assert a.stats()["kv_fetches"] == 1
            wire = encode_kv_handoff(fetched["kv_handoff"])
            got = b.submit({
                "tokens": p, "resume_tokens": delivered,
                "kv_handoff": decode_kv_handoff(wire)})
            assert got["tokens"][0].tolist() == want, (
                "fetch-resume diverged from control")
            assert b.stats()["handoff_pages_in"] > 0
        finally:
            a.close()
            b.close()

    def test_fetch_misses_cleanly(self, lm):
        eng = _engine(lm, kv_pool_blocks=16, host_spill_blocks=16,
                      name="fetch-miss")
        try:
            out = eng.fetch_kv({"tokens": _prompt(12)})
            assert out == {"kv_handoff": None, "tokens_covered": 0}
        finally:
            eng.close()

    def test_fetch_fault_site_fires(self, lm):
        """`engine.fetch:raise` surfaces out of fetch_kv — the serving
        layer answers 500 and the router's fetch leg falls back to
        recompute-resume (router fallback covered in test_fleet)."""
        from kubeflow_tpu.testing import faults

        eng = _engine(lm, kv_pool_blocks=16, host_spill_blocks=16,
                      name="fetch-fault")
        try:
            eng.submit({"tokens": _prompt(12), "park_kv": True})
            inj = faults.parse("engine.fetch:raise")
            faults.install(inj)
            try:
                with pytest.raises(faults.FaultInjected):
                    eng.fetch_kv({"tokens": _prompt(12)})
            finally:
                faults.install(None)
            assert inj.fired("engine.fetch") == 1
        finally:
            eng.close()

    def test_spill_gauges_zeroed_on_close(self, lm):
        from kubeflow_tpu.runtime.prom import REGISTRY, parse_metrics
        from kubeflow_tpu.serving.engine import (
            HOST_TIER_GAUGE,
            KV_SPILLED_GAUGE,
        )

        eng = _engine(lm, kv_pool_blocks=10, host_spill_blocks=32,
                      name="spill-gauge")
        eng.submit({"tokens": _prompt(16), "park_kv": True})

        def series(name):
            parsed = parse_metrics(REGISTRY.render())
            return [v for _, v in parsed.get(name, ())]

        assert any(v > 0 for v in series(KV_SPILLED_GAUGE))
        assert any(v > 0 for v in series(HOST_TIER_GAUGE))
        eng.close()
        assert all(v == 0 for v in series(KV_SPILLED_GAUGE))
        assert all(v == 0 for v in series(HOST_TIER_GAUGE))
