"""Test configuration: fake-slice JAX backend.

The reference could not test its multi-worker GPU paths without renting
hardware (SURVEY.md §4 — it created GCE VMs per CI run).  We do better:
every test runs on a virtual 8-device CPU "slice" via
``--xla_force_host_platform_device_count``, so SPMD sharding, collectives,
and gang logic are exercised hermetically.  bench.py intentionally does NOT
import this — it runs on the real TPU chip.
"""

import os

# Must be set before jax initializes its backends.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402
import pytest  # noqa: E402

# The driver image registers the real-TPU PJRT plugin from sitecustomize and
# pins jax.config.jax_platforms to it at interpreter start, which overrides
# the env var above.  Re-pin to cpu before any backend initializes.
jax.config.update("jax_platforms", "cpu")


# Lock-order sanitizer (KFT_LOCKCHECK=1): the serving/fleet suites
# construct the heavily-threaded objects (engine, batchers, registry,
# router), and the scheduler/supervisor suites are the most
# lock-heavy ones added since (policy + queue + rate-limiter locks;
# supervisor heartbeat/watchdog state), so all four run with
# threading.Lock instrumented.  The sanitizer installs ONCE and the
# acquisition graph accumulates across tests — an inconsistent
# nesting order between two different tests still closes a cycle,
# and the test that closed it fails with both paths spelled out.
# Off by default: instrumentation taxes every acquire, and the
# tier-1 budget is tight.
_LOCKCHECK_MODULES = {"test_serving", "test_fleet", "test_scheduler",
                      "test_supervisor"}


@pytest.fixture(autouse=True)
def _lockcheck(request):
    from kubeflow_tpu.testing import lockcheck

    module = getattr(request, "module", None)
    name = getattr(module, "__name__", "").rsplit(".", 1)[-1]
    if not lockcheck.enabled_in_env() \
            or name not in _LOCKCHECK_MODULES:
        yield
        return
    sanitizer = lockcheck.install()  # idempotent; graph persists
    before = len(sanitizer.violations())
    yield
    new = sanitizer.violations()[before:]
    assert not new, (
        "lock-order inversions recorded (KFT_LOCKCHECK):\n"
        + "\n".join(repr(v) for v in new))


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 fake-slice devices, got {len(devs)}"
    return devs


@pytest.fixture()
def mesh8(devices):
    """A 2x4 {data, model} mesh over the fake slice."""
    import numpy as np
    from jax.sharding import Mesh

    return Mesh(np.array(devices).reshape(2, 4), ("data", "model"))
