"""Fleet control plane: endpoint registry, load-aware router,
autoscaler — driven against scriptable in-process fake replicas (the
real serving surface is exercised by the `fleet` e2e scenario in
kubeflow_tpu/testing/e2e.py; these tests pin the routing/scaling
POLICIES deterministically)."""

import json
import random
import threading
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from kubeflow_tpu.fleet.autoscaler import Autoscaler
from kubeflow_tpu.fleet.endpoints import (
    Endpoint,
    EndpointRegistry,
    KubeEndpoints,
    StaticEndpoints,
)
from kubeflow_tpu.fleet.router import FleetRouter
from kubeflow_tpu.operator.kube import FakeKube
from kubeflow_tpu.testing import faults


class _Replica:
    """Scriptable stand-in for one serving replica: real sockets, fake
    model — /readyz, /metrics (the gauges the registry scrapes), and a
    predict route whose status/behavior the test controls."""

    def __init__(self, port=0):
        self.ready = True
        self.draining = False
        self.inflight = 0.0
        self.queue_depth = 0.0
        self.cached_ratio = 0.0
        self.predict_status = 200
        self.retry_after = None
        self.hang_up = False  # close mid-response without answering
        self.fail_gets = False  # hang up model GETs (stats/metadata)
        self.get_attempts = 0
        self.requests = []
        self.headers_seen = []
        # Streaming :generate script: the full "greedy continuation"
        # this replica produces; a resume_tokens payload makes it emit
        # only the suffix.  gen_die_after severs the connection after
        # that many token lines (mid-generation death); gen_meta is
        # the advertised failover contract.
        self.gen_tokens = list(range(100, 115))
        self.gen_die_after = None
        self.gen_meta = {"resumable": True, "seeded": False}
        # Scripted :fetch_kv answer (§5.10 resume-by-fetch): the
        # kv_handoff payload this replica's host tier "holds" for any
        # context (None = miss), and the route's status code.
        self.fetch_status = 200
        self.fetch_payload = None
        # Disaggregation tier advertised on /readyz (None = omit the
        # key, the pre-tier wire shape) and the scripted :prefill
        # answer — the payload is OPAQUE to the router, which only
        # forwards it into the decode-tier :generate body.
        self.role = None
        # Adapters advertised on /readyz (§5.11): {model: [{name,
        # digest}]} or None to omit the key (pre-adapter wire shape).
        self.adapters = None
        self.prefill_status = 200
        self.prefill_payload = {
            "block_tokens": 4, "tokens_covered": 8,
            "k": {"b64": "AA==", "shape": [1], "dtype": "uint8"},
            "v": {"b64": "AA==", "shape": [1], "dtype": "uint8"}}
        self.lock = threading.Lock()
        replica = self

        class Handler(BaseHTTPRequestHandler):
            # Keep-alive like the real serving handler, so the
            # router's connection pool is exercised by these tests.
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                pass

            def _send(self, code, payload, headers=None):
                data = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                if self.path == "/readyz":
                    extra = {} if replica.role is None \
                        else {"role": replica.role}
                    if replica.adapters is not None:
                        extra["adapters"] = replica.adapters
                    if replica.ready and not replica.draining:
                        self._send(200, dict(
                            {"status": "ready"}, **extra))
                    else:
                        self._send(503, dict(
                            {"status": "draining" if replica.draining
                             else "no models loaded"}, **extra))
                elif self.path == "/metrics":
                    text = (
                        f"kft_serving_inflight {replica.inflight}\n"
                        f'kft_serving_queue_depth{{model="m"}} '
                        f"{replica.queue_depth}\n"
                        f"kft_serving_cached_token_ratio "
                        f"{replica.cached_ratio}\n")
                    data = text.encode()
                    self.send_response(200)
                    self.send_header("Content-Length", str(len(data)))
                    self.end_headers()
                    self.wfile.write(data)
                else:
                    with replica.lock:
                        replica.get_attempts += 1
                    if replica.fail_gets:
                        # Reset, don't close(): a plain close() leaves
                        # the rfile/wfile dups holding the fd open, so
                        # the router blocks the full try_timeout_s
                        # instead of seeing the failure instantly.
                        self._die()
                        return
                    self._send(200, {"route": self.path})

            def _die(self):
                # A crashed process resets the socket; plain close()
                # leaves rfile/wfile refs holding the fd open.
                import socket as _socket

                try:
                    self.connection.shutdown(_socket.SHUT_RDWR)
                except OSError:
                    pass
                self.connection.close()

            def _chunk(self, obj):
                data = json.dumps(obj).encode() + b"\n"
                self.wfile.write(b"%x\r\n" % len(data) + data
                                 + b"\r\n")
                self.wfile.flush()

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(n) if n else b""
                with replica.lock:
                    replica.requests.append((self.path, body))
                    replica.headers_seen.append(
                        dict(self.headers.items()))
                if replica.hang_up:
                    # Bytes were received, then the connection dies —
                    # the transport-failure (replay-eligible) case.
                    self._die()
                    return
                if self.path.endswith(":prefill"):
                    self._send(replica.prefill_status, {
                        "kv_handoff": replica.prefill_payload,
                        "tokens_covered": 0 if not
                        replica.prefill_payload else
                        replica.prefill_payload.get(
                            "tokens_covered", 0)})
                    return
                if self.path.endswith(":fetch_kv"):
                    payload = replica.fetch_payload
                    self._send(replica.fetch_status, {
                        "kv_handoff": payload,
                        "tokens_covered": 0 if payload is None
                        else payload.get("tokens_covered", 0)})
                    return
                if self.path.endswith(":generate"):
                    payload = json.loads(body or b"{}")
                    resume = payload.get("resume_tokens") or []
                    out = replica.gen_tokens[len(resume):]
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "application/x-ndjson")
                    self.send_header("Transfer-Encoding", "chunked")
                    self.end_headers()
                    self._chunk({"meta": dict(replica.gen_meta)})
                    for i, tok in enumerate(out):
                        if replica.gen_die_after is not None \
                                and i >= replica.gen_die_after:
                            self._die()
                            return
                        self._chunk({"tokens": [tok]})
                    self._chunk({"done": True,
                                 "tokens_emitted": len(out)})
                    self.wfile.write(b"0\r\n\r\n")
                    return
                headers = {}
                if replica.retry_after is not None:
                    headers["Retry-After"] = str(replica.retry_after)
                self._send(replica.predict_status,
                           {"predictions": [{"ok": True}]}, headers)

        self.httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self.port = self.httpd.server_address[1]
        self.url = f"http://127.0.0.1:{self.port}"
        self.thread = threading.Thread(target=self.httpd.serve_forever,
                                       daemon=True)
        self.thread.start()

    def kill(self):
        self.httpd.shutdown()
        self.httpd.server_close()

    def received(self):
        with self.lock:
            return list(self.requests)


def _registry(replicas, **kw):
    kw.setdefault("eject_threshold", 2)
    kw.setdefault("rng", random.Random(0))
    reg = EndpointRegistry(
        StaticEndpoints([Endpoint(name=f"r{i}", url=r.url)
                         for i, r in enumerate(replicas)]), **kw)
    reg.refresh()
    return reg


def _router(reg, **kw):
    kw.setdefault("rng", random.Random(0))
    kw.setdefault("try_timeout_s", 10.0)
    return FleetRouter(reg, **kw)


@pytest.fixture()
def replicas():
    reps = [_Replica() for _ in range(3)]
    yield reps
    for r in reps:
        try:
            r.kill()
        except Exception:
            pass


def _predict(router, body=None, path="/model/m:predict"):
    payload = json.dumps(body or {"instances": [[1]]}).encode()
    return router.handle("POST", path, payload,
                         {"Content-Type": "application/json"})


class TestRegistry:
    def test_discovery_and_readiness(self, replicas):
        reg = _registry(replicas)
        assert len(reg.all()) == 3
        assert len(reg.routable()) == 3
        replicas[1].ready = False
        reg.refresh()
        routable = {s.name for s in reg.routable()}
        assert routable == {"r0", "r2"}

    def test_draining_replica_not_routable_but_not_ejected(
            self, replicas):
        reg = _registry(replicas)
        replicas[0].draining = True
        reg.refresh()
        states = {s.name: s for s in reg.all()}
        assert not states["r0"].routable()
        assert states["r0"].state_label() == "draining"
        assert not states["r0"].breaker.open

    def test_load_scraped_from_metrics(self, replicas):
        replicas[2].inflight = 7
        replicas[2].queue_depth = 3
        replicas[2].cached_ratio = 0.42
        reg = _registry(replicas)
        states = {s.name: s for s in reg.all()}
        assert states["r2"].score() == 10.0
        assert reg.total_load() == 10.0
        # Prefix-cache effectiveness rides the same scrape and surfaces
        # per replica (fleet status CACHE% column / router gauge) —
        # but never enters the P2C load score.
        assert states["r2"].cached_token_ratio == 0.42
        assert states["r0"].cached_token_ratio == 0.0
        rows = {r["name"]: r for r in reg.describe()}
        assert rows["r2"]["cached_token_ratio"] == 0.42
        from kubeflow_tpu.runtime.prom import REGISTRY, parse_metrics
        from kubeflow_tpu.runtime.prom import sample_value

        parsed = parse_metrics(REGISTRY.render())
        assert sample_value(parsed, "kft_router_cached_token_ratio",
                            endpoint="r2") == 0.42

    def test_dead_replica_ejected_after_threshold_probes(
            self, replicas):
        with faults.injected("seed=0"):
            reg = _registry(replicas, eject_threshold=2,
                            eject_backoff_s=5.0)
            replicas[0].kill()
            reg.refresh()  # failure 1
            reg.refresh()  # failure 2 -> ejected
            states = {s.name: s for s in reg.all()}
            assert states["r0"].breaker.open
            assert states["r0"].state_label() == "ejected"
            # While open, further refreshes skip the probe entirely.
            fired = faults.active().fired("fleet.probe")
            reg.refresh()
            assert faults.active().fired("fleet.probe") == fired + 2

    def test_ejected_replica_recovers_via_half_open_probe(self):
        rep = _Replica()
        fresh = None
        try:
            with faults.injected("seed=0") as inj:
                reg = _registry([rep], eject_threshold=1,
                                eject_backoff_s=5.0)
                port = rep.port
                rep.kill()
                reg.refresh()
                state = reg.all()[0]
                assert state.breaker.open
                # Backoff not yet expired: probe stays skipped and the
                # endpoint stays ejected.
                reg.refresh()
                assert state.breaker.open
                # Replica comes back on the same port; after the
                # (clock-skewed) backoff the half-open trial probe
                # runs, succeeds, and closes the breaker.
                fresh = _Replica(port=port)
                inj.advance_clock(30)
                reg.refresh()
                assert not state.breaker.open
                assert state.routable()
        finally:
            if fresh is not None:
                fresh.kill()

    def test_describe_renders_all_states_without_deadlock(
            self, replicas):
        # Regression: describe() once re-acquired the (non-reentrant)
        # state lock through state_label() — deadlocking the router's
        # /fleet/endpoints route for any NON-ejected endpoint.
        reg = _registry(replicas)
        replicas[1].draining = True
        reg.refresh()
        done = []
        t = threading.Thread(target=lambda: done.append(reg.describe()))
        t.start()
        t.join(timeout=10)
        assert done, "describe() deadlocked"
        states = {r["name"]: r["state"] for r in done[0]}
        assert states["r0"] == "routable"
        assert states["r1"] == "draining"

    def test_half_open_trial_released_when_probe_answers_not_ready(
            self):
        """Regression: an ejected endpoint whose half-open probe finds
        the replica alive-but-loading (/readyz 503, not draining) must
        RELEASE the trial slot — it once stayed claimed forever,
        permanently ejecting a replica that later became healthy."""
        rep = _Replica()
        try:
            with faults.injected("seed=0") as inj:
                reg = _registry([rep], eject_threshold=1,
                                eject_backoff_s=2.0)
                port = rep.port
                rep.kill()
                reg.refresh()
                state = reg.all()[0]
                assert state.breaker.open
                # Replica returns but is NOT ready yet (no models).
                back = _Replica(port=port)
                back.ready = False
                inj.advance_clock(10)
                reg.refresh()  # half-open trial: alive, 503 not-ready
                assert state.breaker.open  # still ejected...
                back.ready = True
                inj.advance_clock(10)  # ...but a LATER window re-probes
                reg.refresh()
                assert not state.breaker.open
                assert state.routable()
                back.kill()
        finally:
            pass

    def test_kube_port_prefers_named_http_over_sidecar(self):
        kube = FakeKube()
        kube.create_pod({
            "metadata": {"namespace": "kf", "name": "srv-0",
                         "labels": {"app": "srv"}},
            "spec": {"containers": [
                {"ports": [{"name": "http", "containerPort": 8000}]},
                {"ports": [{"containerPort": 9090}]},  # sidecar
            ]},
            "status": {"podIP": "10.0.0.5"}})
        kube.set_pod_phase("kf", "srv-0", "Running")
        src = KubeEndpoints(kube, "kf", {"app": "srv"})
        assert src.discover()[0].url == "http://10.0.0.5:8000"

    def test_kube_endpoint_source_reads_running_pods(self):
        kube = FakeKube()
        kube.create_pod({
            "metadata": {"namespace": "kf", "name": "srv-0",
                         "labels": {"app": "srv"}},
            "spec": {"containers": [{
                "ports": [{"name": "http", "containerPort": 8123}]}]},
            "status": {"podIP": "10.0.0.5"}})
        kube.set_pod_phase("kf", "srv-0", "Running")
        kube.create_pod({  # pending pod: no endpoint yet
            "metadata": {"namespace": "kf", "name": "srv-1",
                         "labels": {"app": "srv"}},
            "spec": {"containers": []},
            "status": {"podIP": "10.0.0.6"}})
        src = KubeEndpoints(kube, "kf", {"app": "srv"})
        eps = src.discover()
        assert [e.name for e in eps] == ["srv-0"]
        assert eps[0].url == "http://10.0.0.5:8123"


class TestRouter:
    def test_p2c_prefers_lower_load(self, replicas):
        replicas[0].inflight = 50
        replicas[1].inflight = 50
        replicas[2].inflight = 0
        reg = _registry(replicas)
        router = _router(reg)
        # With two candidates compared per pick, the idle replica wins
        # every draw it appears in; over many requests it must carry
        # the clear majority.
        for _ in range(30):
            status, _, _ = _predict(router)
            assert status == 200
        counts = [len(r.received()) for r in replicas]
        assert counts[2] > counts[0] and counts[2] > counts[1]

    def test_overloaded_replica_retried_on_other(self, replicas):
        replicas[0].predict_status = 429
        replicas[0].retry_after = 3
        replicas[1].predict_status = 429
        replicas[1].retry_after = 3
        reg = _registry(replicas)
        router = _router(reg)
        for _ in range(5):
            status, headers, body = _predict(router)
            assert status == 200, body
        assert len(replicas[2].received()) >= 5
        # Shed responses are health, not sickness: nobody ejected.
        assert not any(s.breaker.open for s in reg.all())

    def test_all_overloaded_propagates_min_retry_after(self, replicas):
        for r, hint in zip(replicas, (7, 3, 9)):
            r.predict_status = 429
            r.retry_after = hint
        reg = _registry(replicas)
        router = _router(reg)
        status, headers, body = _predict(router)
        assert status == 429
        assert headers["Retry-After"] == "3"

    def test_dead_replica_request_retried_and_ejected(self, replicas):
        reg = _registry(replicas, eject_threshold=2)
        router = _router(reg)
        replicas[0].kill()
        # Every request succeeds (connection-refused retries on a
        # different replica) and the dead one accumulates failures
        # until ejection takes it out of rotation.
        for _ in range(10):
            status, _, body = _predict(router)
            assert status == 200, body
        states = {s.name: s for s in reg.all()}
        assert states["r0"].breaker.open

    def test_post_transport_failure_replayed_with_same_key(self):
        """A model POST whose bytes reached a replica IS replayed now:
        every attempt carries one idempotency key (minted here — no
        client header), so re-execution is dedup-safe, and the client
        gets the answer a healthy replica produced."""
        dying, healthy = _Replica(), _Replica()
        dying.hang_up = True
        # P2C always prefers the (lower-scored) dying replica first,
        # so every request exercises the replay path.
        healthy.inflight = 50
        try:
            reg = _registry([dying, healthy])
            router = _router(reg)
            status, _, body = _predict(router)
            assert status == 200, body
            assert len(dying.received()) == 1
            assert len(healthy.received()) == 1
            # One key, both attempts: the replica that died saw the
            # SAME x-kft-idempotency-key the survivor answered under.
            keys = {h.get("x-kft-idempotency-key")
                    for r in (dying, healthy) for h in r.headers_seen}
            assert len(keys) == 1 and None not in keys, keys
            from kubeflow_tpu.runtime.prom import (
                REGISTRY,
                parse_metrics,
                sample_value,
            )

            parsed = parse_metrics(REGISTRY.render())
            assert (sample_value(parsed, "kft_router_replays_total",
                                 outcome="ok") or 0) >= 1
        finally:
            dying.kill()
            healthy.kill()

    def test_post_client_key_forwarded_verbatim(self, replicas):
        reg = _registry(replicas)
        router = _router(reg)
        status, _, _ = router.handle(
            "POST", "/model/m:predict",
            json.dumps({"instances": [[1]]}).encode(),
            {"X-KFT-Idempotency-Key": "client-key-7"})
        assert status == 200
        keys = [h.get("x-kft-idempotency-key")
                for r in replicas for h in r.headers_seen]
        assert keys == ["client-key-7"]

    def test_post_replay_cap_zero_restores_502(self, replicas):
        """max_replays=0 is the pre-replay contract: a transport
        failure after bytes reached a replica answers 502 and exactly
        ONE replica ever saw the request."""
        for r in replicas:
            r.hang_up = True
        reg = _registry(replicas)
        router = _router(reg, max_replays=0)
        status, _, _ = _predict(router)
        assert status == 502
        assert sum(len(r.received()) for r in replicas) == 1

    def test_post_replay_cap_bounds_attempts(self, replicas):
        """Every replica dying caps the request at 1 original +
        max_replays attempts, then 502."""
        for r in replicas:
            r.hang_up = True
        reg = _registry(replicas)
        router = _router(reg, max_replays=2)
        status, _, _ = _predict(router)
        assert status == 502
        assert sum(len(r.received()) for r in replicas) == 3

    def test_non_model_post_never_replayed(self, replicas):
        """POSTs outside the model routes have unknown side effects:
        the never-replay 502 contract is unchanged for them."""
        for r in replicas:
            r.hang_up = True
        reg = _registry(replicas)
        router = _router(reg)
        status, _, _ = _predict(router, path="/admin/do-something")
        assert status == 502
        assert sum(len(r.received()) for r in replicas) == 1
        # And no idempotency key was invented for it.
        keys = [h.get("x-kft-idempotency-key")
                for r in replicas for h in r.headers_seen]
        assert keys == [None]

    def test_post_on_reused_conn_death_recovers_via_replay(self):
        """A pooled keep-alive connection dying before the response is
        indistinguishable from a replica crashing mid-request — under
        the idempotency key that is now REPLAYABLE instead of a 502."""
        rep, other = _Replica(), _Replica()
        try:
            reg = _registry([rep, other])
            router = _router(reg)
            # Warm the pool: route until BOTH replicas served once.
            for _ in range(10):
                status, _, _ = _predict(router)
                assert status == 200
                if rep.received() and other.received():
                    break
            assert rep.received(), "pool to rep never warmed"
            rep.hang_up = True
            other.hang_up = False
            status, _, _ = _predict(router)
            assert status == 200
        finally:
            rep.kill()
            other.kill()

    def test_probe_driven_ejection_purges_router_pool(self):
        """Regression: only ROUTER-observed failures purged the
        keep-alive pool; a probe-driven ejection left stale pooled
        connections that greeted the recovered replica's first POST
        with a non-retryable transport failure."""
        rep = _Replica()
        fresh = None
        try:
            with faults.injected("seed=0") as inj:
                reg = _registry([rep], eject_threshold=1,
                                eject_backoff_s=2.0)
                router = _router(reg)
                status, _, _ = _predict(router)
                assert status == 200  # a conn is now pooled
                assert router._pool.get(rep.url) is not None
                # Re-pool it and crash the replica; the PROBE ejects.
                status, _, _ = _predict(router)
                port = rep.port
                rep.kill()
                reg.refresh()
                state = reg.all()[0]
                assert state.breaker.open
                # Pool purged by the on_eject hook:
                assert router._pool.get(rep.url) is None
                # Recovery: replica back on the same port; its first
                # routed POST must ride a FRESH connection and win.
                fresh = _Replica(port=port)
                inj.advance_clock(10)
                reg.refresh()
                assert state.routable()
                status, _, body = _predict(router)
                assert status == 200, body
        finally:
            if fresh is not None:
                fresh.kill()

    def test_get_is_retried_on_transport_failure(self):
        # GETs are idempotent: a mid-flight transport failure IS
        # retried on the other replica (the POST twin of this scenario
        # answers 502 — see the non-idempotent test above).
        bad, good = _Replica(), _Replica()
        bad.fail_gets = True
        try:
            reg = _registry([bad, good])
            # Map scripted replicas to their registry names for the
            # assertion below (r0 = bad, r1 = good).
            router = _router(reg, max_tries=3)
            for _ in range(10):
                status, _, _ = router.handle(
                    "GET", "/model/m:stats", b"", {})
                assert status == 200
            # The failing replica was offered at least one GET, which
            # then completed elsewhere: that is a retry.
            assert bad.get_attempts > 0
        finally:
            bad.kill()
            good.kill()

    def test_expired_deadline_never_reaches_a_replica(self, replicas):
        reg = _registry(replicas)
        router = _router(reg)
        # A ~100ns budget expires between arrival and the pre-forward
        # re-check (Python overhead alone is microseconds): the router
        # answers 504 itself without opening any upstream socket.
        status, _, _ = router.handle(
            "POST", "/model/m:predict",
            json.dumps({"instances": [[1]],
                        "deadline_ms": 0.0001}).encode(), {})
        assert status == 504
        assert sum(len(r.received()) for r in replicas) == 0

    def test_deadline_rewritten_to_remaining_budget(self, replicas):
        reg = _registry(replicas)
        router = _router(reg)
        status, _, _ = _predict(
            router, {"instances": [[1]], "deadline_ms": 60000})
        assert status == 200
        path, body = [r for r in replicas if r.received()][0].received()[0]
        forwarded = json.loads(body)["deadline_ms"]
        assert 0 < forwarded <= 60000

    def test_retry_budget_bounds_amplification(self, replicas):
        for r in replicas:
            r.predict_status = 429
            r.retry_after = 1
        reg = _registry(replicas)
        router = _router(reg, retry_budget_ratio=0.0,
                         retry_budget_cap=0.0)
        status, _, _ = _predict(router)
        assert status == 429
        # Budget empty: exactly one replica was offered the request.
        assert sum(len(r.received()) for r in replicas) == 1

    def test_draining_replica_gets_no_new_work(self, replicas):
        reg = _registry(replicas)
        router = _router(reg)
        replicas[0].draining = True
        reg.refresh()
        for _ in range(10):
            status, _, _ = _predict(router)
            assert status == 200
        assert len(replicas[0].received()) == 0

    def test_no_routable_replicas_is_503(self, replicas):
        reg = _registry(replicas)
        for r in replicas:
            r.ready = False
        reg.refresh()
        router = _router(reg)
        status, _, body = _predict(router)
        assert status == 503
        assert b"no routable" in body


class TestAdapterAffinity:
    """model@adapter routing (§5.11): /readyz advertisement -> warm-
    subset preference in pick(), with full-pool P2C fallback on miss
    (the cold replica hot-loads; affinity is a preference, never a
    hard constraint)."""

    def test_readyz_adapters_parsed_into_state(self, replicas):
        replicas[0].adapters = {
            "m": [{"name": "a", "digest": "d1"},
                  {"name": "b", "digest": "d2"}]}
        reg = _registry(replicas)
        states = {s.name: s for s in reg.all()}
        assert states["r0"].has_adapter("m", "a")
        assert states["r0"].has_adapter("m", "b")
        assert not states["r0"].has_adapter("m", "zz")
        assert not states["r0"].has_adapter("other", "a")
        assert not states["r1"].has_adapter("m", "a")
        row = next(r for r in reg.describe() if r["name"] == "r0")
        assert row["adapters"] == {"m": ["a", "b"]}
        # A replica that stops advertising loses its affinity (evict).
        replicas[0].adapters = {"m": [{"name": "b", "digest": "d2"}]}
        reg.refresh()
        states = {s.name: s for s in reg.all()}
        assert not states["r0"].has_adapter("m", "a")
        assert states["r0"].has_adapter("m", "b")

    def test_path_adapter_parse(self):
        f = FleetRouter._path_adapter
        assert f("/model/m@a:predict") == ("m", "a")
        assert f("/model/m@a") == ("m", "a")
        assert f("/model/m@a:generate") == ("m", "a")
        assert f("/model/m:predict") is None
        assert f("/model/m@:predict") is None
        assert f("/model/m/versions/1:predict") is None
        assert f("/healthz") is None

    def test_pick_prefers_warm_replica(self, replicas):
        from kubeflow_tpu.runtime.prom import (
            REGISTRY,
            parse_metrics,
            sample_value,
        )

        replicas[2].adapters = {
            "m": [{"name": "a", "digest": "d1"}]}
        reg = _registry(replicas)
        router = _router(reg)

        def affinity(outcome):
            return sample_value(
                parse_metrics(REGISTRY.render()),
                "kft_router_adapter_affinity_total",
                outcome=outcome) or 0.0

        hits = affinity("hit")
        for _ in range(8):
            assert router.pick(adapter=("m", "a")).name == "r2"
        assert affinity("hit") == hits + 8
        # Unknown adapter: nobody is warm — full-pool P2C fallback
        # (the picked replica will hot-load it on demand).
        misses = affinity("miss")
        picked = {router.pick(adapter=("m", "zz")).name
                  for _ in range(24)}
        assert len(picked) > 1
        assert affinity("miss") == misses + 24
        # Plain pick()s never touch the affinity counter.
        hits, misses = affinity("hit"), affinity("miss")
        router.pick()
        assert (affinity("hit"), affinity("miss")) == (hits, misses)

    def test_routed_predict_lands_on_warm_replica(self, replicas):
        replicas[1].adapters = {
            "m": [{"name": "a", "digest": "d1"}]}
        reg = _registry(replicas)
        router = _router(reg)
        for _ in range(5):
            status, _, _ = _predict(router,
                                    path="/model/m@a:predict")
            assert status == 200
        assert len(replicas[1].received()) == 5
        assert all(p == "/model/m@a:predict"
                   for p, _ in replicas[1].received())
        # The warm replica draining must not strand the adapter:
        # fallback routes to the cold pool.
        replicas[1].draining = True
        reg.refresh()
        status, _, _ = _predict(router, path="/model/m@a:predict")
        assert status == 200
        assert len(replicas[1].received()) == 5


class _Sink:
    """Transport-independent client side for router.handle_stream."""

    def __init__(self):
        self.started = False
        self.lines = []

    def start(self):
        self.started = True

    def write_line(self, payload):
        self.started = True
        self.lines.append(payload)

    def tokens(self):
        return [t for m in self.lines for t in m.get("tokens", [])]


def _stream(router, body=None, headers=None):
    sink = _Sink()
    plain = router.handle_stream(
        "/model/m:generate",
        json.dumps(body or {"tokens": [1, 2, 3]}).encode(),
        headers or {}, sink)
    return plain, sink


class TestStreamingFailover:
    """Mid-generation failover on the :generate stream proxy: resume
    splicing, seeded skip-splicing, the unseeded-sampling 502, budget
    and cap denials, immediate force-ejection, and the router.replay
    trace spans."""

    def _pair(self, die_after=5):
        dying, survivor = _Replica(), _Replica()
        dying.gen_die_after = die_after
        # P2C deterministically offers the dying replica first.
        survivor.inflight = 50
        reg = _registry([dying, survivor])
        return dying, survivor, reg

    def test_resume_splice_is_gapless_and_duplicate_free(self):
        dying, survivor, reg = self._pair(die_after=5)
        try:
            router = _router(reg)
            plain, sink = _stream(router)
            assert plain is None
            assert sink.tokens() == dying.gen_tokens, sink.lines
            assert sink.lines[-1] == {
                "done": True, "tokens_emitted": len(dying.gen_tokens)}
            # The survivor was asked to RESUME: prompt + the 5 tokens
            # the client already held, same idempotency key.  (A
            # resumable replay also tries the :fetch_kv leg first —
            # TestFetchResume pins that — so filter for :generate.)
            path, body = [r for r in survivor.received()
                          if r[0].endswith(":generate")][0]
            payload = json.loads(body)
            assert payload["resume_tokens"] == dying.gen_tokens[:5]
            keys = {h.get("x-kft-idempotency-key")
                    for r in (dying, survivor) for h in r.headers_seen}
            assert len(keys) == 1 and None not in keys
        finally:
            dying.kill()
            survivor.kill()

    def test_seeded_sampling_replays_from_scratch_and_skips(self):
        """No resume payload without determinism — but a recorded seed
        reproduces the stream, so the router re-runs it and SKIPS the
        delivered prefix."""
        dying, survivor, reg = self._pair(die_after=4)
        dying.gen_meta = {"resumable": False, "seeded": True}
        survivor.gen_meta = {"resumable": False, "seeded": True}
        try:
            router = _router(reg)
            plain, sink = _stream(router)
            assert plain is None
            assert sink.tokens() == dying.gen_tokens, sink.lines
            # From scratch: the survivor got NO resume payload and
            # re-emitted everything; the router dropped the overlap.
            _, body = survivor.received()[0]
            assert "resume_tokens" not in json.loads(body)
        finally:
            dying.kill()
            survivor.kill()

    def test_unseeded_sampling_keeps_502_semantics(self):
        dying, survivor, reg = self._pair(die_after=5)
        dying.gen_meta = {"resumable": False, "seeded": False}
        try:
            router = _router(reg)
            plain, sink = _stream(router)
            # Tokens already streamed: the failure is a terminal error
            # line, and nothing ran on the survivor.
            assert plain is None
            err = sink.lines[-1]
            assert err.get("code") == 502, sink.lines
            assert survivor.received() == []
        finally:
            dying.kill()
            survivor.kill()

    def test_death_before_any_token_replays_fresh(self):
        """Nothing delivered => any fresh attempt is safe even for an
        unseeded sampler (the client holds no prefix to contradict)."""
        dying, survivor, reg = self._pair(die_after=0)
        dying.gen_meta = {"resumable": False, "seeded": False}
        survivor.gen_meta = {"resumable": False, "seeded": False}
        try:
            router = _router(reg)
            plain, sink = _stream(router)
            assert plain is None
            assert sink.tokens() == dying.gen_tokens
            _, body = survivor.received()[0]
            assert "resume_tokens" not in json.loads(body)
        finally:
            dying.kill()
            survivor.kill()

    def test_mid_generation_death_force_ejects_immediately(self):
        dying, survivor, reg = self._pair(die_after=5)
        try:
            router = _router(reg)
            _stream(router)
            states = {s.name: s for s in reg.all()}
            # No probe pass ran: the stream death itself ejected it.
            assert states["r0"].breaker.open
            assert states["r0"].breaker.state() in ("open",
                                                    "half_open")
            assert not states["r1"].breaker.open
        finally:
            dying.kill()
            survivor.kill()

    def test_replay_cap_zero_truncates_stream(self):
        dying, survivor, reg = self._pair(die_after=5)
        try:
            router = _router(reg, max_replays=0)
            plain, sink = _stream(router)
            assert plain is None
            assert sink.lines[-1].get("code") == 502
            assert survivor.received() == []
        finally:
            dying.kill()
            survivor.kill()

    def test_replay_budget_exhaustion_denies_failover(self):
        dying, survivor, reg = self._pair(die_after=5)
        try:
            router = _router(reg, retry_budget_ratio=0.0,
                             retry_budget_cap=0.0)
            plain, sink = _stream(router)
            assert plain is None
            assert sink.lines[-1].get("code") == 502
            assert survivor.received() == []
        finally:
            dying.kill()
            survivor.kill()

    def test_pre_stream_failure_answers_plain_status(self):
        """Failures before any stream byte keep ordinary status-code
        responses — here: no routable replicas -> a plain 503, the
        sink untouched."""
        rep = _Replica()
        try:
            reg = _registry([rep])
            router = _router(reg)
            for r in reg.all():
                with r._lock:
                    r.ready = False
            plain, sink = _stream(router)
            assert plain is not None
            assert plain[0] == 503
            assert not sink.started
        finally:
            rep.kill()

    def test_recovered_stream_trace_has_replay_span(self):
        from kubeflow_tpu.runtime import tracing

        dying, survivor, reg = self._pair(die_after=5)
        tracing.enable(sample_rate=0.0, capacity=32)
        try:
            router = _router(reg)
            plain, sink = _stream(router)
            assert plain is None
            assert sink.tokens() == dying.gen_tokens
            traces = tracing.store().traces()
            # sample_rate 0: only the error tier retains — and a
            # failed-then-RECOVERED request rides it by design.
            assert len(traces) == 1, [t["status"] for t in traces]
            trace = traces[0]
            assert trace["status"] == "recovered"
            assert trace["retained"] == "error"
            by_name = {}
            for s in trace["spans"]:
                by_name.setdefault(s["name"], s)
            root = by_name["router.request"]
            assert root["parent_id"] is None
            fwd = by_name["router.forward"]
            replay = by_name["router.replay"]
            # Both attempts hang under the one root request span.
            assert fwd["parent_id"] == root["span_id"]
            assert replay["parent_id"] == root["span_id"]
            # The replay span names the dead replica and the resume
            # depth the survivor continued from.
            assert replay["attrs"]["dead"] == "r0"
            assert replay["attrs"]["replica"] == "r1"
            assert replay["attrs"]["resume_tokens"] == 5
        finally:
            tracing.disable()
            dying.kill()
            survivor.kill()


def _tier_ctr(tier):
    from kubeflow_tpu.runtime.prom import (
        REGISTRY,
        parse_metrics,
        sample_value,
    )

    return sample_value(parse_metrics(REGISTRY.render()),
                        "kft_router_tier_requests_total",
                        tier=tier) or 0


def _fetch_count(outcome):
    from kubeflow_tpu.runtime.prom import (
        REGISTRY,
        parse_metrics,
        sample_value,
    )

    parsed = parse_metrics(REGISTRY.render())
    return sample_value(parsed, "kft_router_kv_fetch_total",
                        outcome=outcome) or 0


class TestFetchResume:
    """Resume-by-fetch (§5.10): before the recompute resume, the
    router asks surviving peers' :fetch_kv for the broken session's
    parked/spilled KV pages and folds a hit into the replay body;
    every failure mode must fall back to the plain recompute resume
    (fetch only makes resume cheap, never makes it possible)."""

    _HANDOFF = {"block_tokens": 4, "tokens_covered": 8,
                "k": {"b64": "AA==", "shape": [1], "dtype": "uint8"},
                "v": {"b64": "AA==", "shape": [1], "dtype": "uint8"}}

    def _pair(self, die_after=5):
        dying, survivor = _Replica(), _Replica()
        dying.gen_die_after = die_after
        survivor.inflight = 50  # P2C offers the dying replica first
        reg = _registry([dying, survivor])
        return dying, survivor, reg

    def _split(self, replica):
        reqs = replica.received()
        return ([json.loads(b) for p, b in reqs
                 if p.endswith(":fetch_kv")],
                [json.loads(b) for p, b in reqs
                 if p.endswith(":generate")])

    def test_fetch_hit_attaches_handoff_to_replay(self):
        dying, survivor, reg = self._pair()
        survivor.fetch_payload = dict(self._HANDOFF)
        before = _fetch_count("ok")
        try:
            router = _router(reg)
            plain, sink = _stream(router)
            assert plain is None
            assert sink.tokens() == dying.gen_tokens, sink.lines
            fetches, gens = self._split(survivor)
            # The fetch asked for the FULL broken context: prompt +
            # the tokens the client already holds.
            assert len(fetches) == 1
            assert fetches[0]["tokens"] == \
                [1, 2, 3] + dying.gen_tokens[:5]
            # The replay body carries both resume halves: the
            # delivered prefix AND the fetched pages.
            assert gens[0]["resume_tokens"] == dying.gen_tokens[:5]
            assert gens[0]["kv_handoff"] == self._HANDOFF
            assert _fetch_count("ok") == before + 1
        finally:
            dying.kill()
            survivor.kill()

    def test_fetch_miss_falls_back_to_recompute_resume(self):
        dying, survivor, reg = self._pair()
        before = _fetch_count("miss")
        try:
            router = _router(reg)
            plain, sink = _stream(router)
            assert sink.tokens() == dying.gen_tokens, sink.lines
            fetches, gens = self._split(survivor)
            assert len(fetches) == 1  # asked, answered "don't hold it"
            assert "kv_handoff" not in gens[0]
            assert gens[0]["resume_tokens"] == dying.gen_tokens[:5]
            assert _fetch_count("miss") == before + 1
        finally:
            dying.kill()
            survivor.kill()

    def test_fetch_error_falls_back_to_recompute_resume(self):
        dying, survivor, reg = self._pair()
        survivor.fetch_status = 500
        before = _fetch_count("error")
        try:
            router = _router(reg)
            plain, sink = _stream(router)
            # The fetch leg failing must not cost the stream anything.
            assert sink.tokens() == dying.gen_tokens, sink.lines
            _, gens = self._split(survivor)
            assert "kv_handoff" not in gens[0]
            assert gens[0]["resume_tokens"] == dying.gen_tokens[:5]
            assert _fetch_count("error") == before + 1
        finally:
            dying.kill()
            survivor.kill()

    def test_seeded_replay_never_fetches(self):
        """A seeded non-resumable stream replays from scratch — its
        replay body has no resume_tokens, so a fetched handoff would
        exceed the prompt and the engine would 400 it.  The fetch leg
        is resumable-only."""
        dying, survivor, reg = self._pair(die_after=4)
        dying.gen_meta = {"resumable": False, "seeded": True}
        survivor.gen_meta = {"resumable": False, "seeded": True}
        survivor.fetch_payload = dict(self._HANDOFF)
        try:
            router = _router(reg)
            plain, sink = _stream(router)
            assert sink.tokens() == dying.gen_tokens, sink.lines
            fetches, gens = self._split(survivor)
            assert fetches == []
            assert "kv_handoff" not in gens[0]
            assert "resume_tokens" not in gens[0]
        finally:
            dying.kill()
            survivor.kill()


class TestTieredRouting:
    """Disaggregated prefill/decode topology (§5.9): replicas
    advertise --role on /readyz, the registry learns the tier, and the
    router pipelines :generate prefill-then-decode — falling back to
    the untiered path on ANY prefill-leg failure and shedding typed
    429 Overloaded when the decode pool dies mid-handoff."""

    def _fleet(self):
        pre, dec, uni = _Replica(), _Replica(), _Replica()
        pre.role = "prefill"
        dec.role = "decode"
        reg = _registry([pre, dec, uni])
        return pre, dec, uni, reg

    def _kill(self, *reps):
        for r in reps:
            try:
                r.kill()
            except Exception:
                pass

    def test_registry_learns_tiers(self):
        pre, dec, uni, reg = self._fleet()
        try:
            tiers = {s.name: s.tier for s in reg.all()}
            assert tiers == {"r0": "prefill", "r1": "decode",
                             "r2": "unified"}
            rows = {r["name"]: r["tier"] for r in reg.describe()}
            assert rows == tiers
        finally:
            self._kill(pre, dec, uni)

    def test_generate_pipelines_prefill_then_decode(self):
        pre, dec, uni, reg = self._fleet()
        try:
            router = _router(reg)
            p0, d0 = _tier_ctr("prefill"), _tier_ctr("decode")
            plain, sink = _stream(router)
            assert plain is None
            assert sink.tokens() == dec.gen_tokens
            # The prefill pool got exactly the :prefill leg...
            assert [p for p, _ in pre.received()] \
                == ["/model/m:prefill"]
            # ...and the decode replica's :generate body carries the
            # handoff payload VERBATIM (the router never decodes it).
            path, body = dec.received()[0]
            assert path == "/model/m:generate"
            assert json.loads(body)["kv_handoff"] \
                == pre.prefill_payload
            # The unified replica stayed out of the tiered pipeline.
            assert uni.received() == []
            assert _tier_ctr("prefill") == p0 + 1
            assert _tier_ctr("decode") == d0 + 1
        finally:
            self._kill(pre, dec, uni)

    def test_prefill_failure_falls_back_untiered(self):
        pre, dec, uni, reg = self._fleet()
        pre.prefill_status = 500
        try:
            router = _router(reg)
            u0 = _tier_ctr("unified")
            plain, sink = _stream(router)
            assert plain is None
            assert sink.tokens() == dec.gen_tokens
            # Untiered fallback: no :generate body grew a handoff key.
            for r in (pre, dec, uni):
                for path, body in r.received():
                    if path.endswith(":generate"):
                        assert "kv_handoff" not in json.loads(body)
            assert _tier_ctr("unified") == u0 + 1
        finally:
            self._kill(pre, dec, uni)

    def test_short_prompt_null_handoff_falls_back(self):
        pre, dec, uni, reg = self._fleet()
        pre.prefill_payload = None  # prompt under one full page
        try:
            router = _router(reg)
            plain, sink = _stream(router)
            assert plain is None
            assert sink.tokens() == dec.gen_tokens
            for r in (pre, dec, uni):
                for path, body in r.received():
                    if path.endswith(":generate"):
                        assert "kv_handoff" not in json.loads(body)
        finally:
            self._kill(pre, dec, uni)

    def test_decode_death_mid_handoff_sheds_429_not_hang(self):
        """The ONLY decode replica dies mid-handoff: force-ejected,
        the replay pick finds no decode-tier candidate, and the
        stream terminates with a typed 429 Overloaded line — one-tier
        overload is capacity to retry into, never a hang or a 502."""
        pre, dec, uni, reg = self._fleet()
        dec.gen_die_after = 2
        try:
            router = _router(reg)
            plain, sink = _stream(router)
            assert plain is None  # the 200 stream had begun
            last = sink.lines[-1]
            assert last.get("code") == 429, sink.lines
            # Proof of death, not weather: ejected immediately.
            state = [s for s in reg.all() if s.name == "r1"][0]
            assert state.breaker.open
        finally:
            self._kill(pre, dec, uni)

    def test_tier_dispatch_fault_falls_back(self):
        pre, dec, uni, reg = self._fleet()
        try:
            router = _router(reg)
            inj = faults.parse("router.tier_dispatch:raise")
            faults.install(inj)
            try:
                plain, sink = _stream(router)
            finally:
                faults.install(None)
            assert inj.fired("router.tier_dispatch") == 1
            assert plain is None
            assert sink.tokens() == dec.gen_tokens
            # The scripted tier failure skipped the prefill leg
            # entirely; the request served untiered.
            assert pre.received() == [] or not any(
                p.endswith(":prefill") for p, _ in pre.received())
        finally:
            self._kill(pre, dec, uni)


class TestAutoscaler:
    def _deployment(self, kube, replicas=1):
        kube.create_deployment({
            "metadata": {"namespace": "kf", "name": "srv"},
            "spec": {"replicas": replicas}})

    def _scaler(self, kube, reg, **kw):
        kw.setdefault("target_inflight_per_replica", 4.0)
        kw.setdefault("min_replicas", 1)
        kw.setdefault("max_replicas", 8)
        kw.setdefault("scale_up_cooldown_s", 10.0)
        kw.setdefault("scale_down_cooldown_s", 60.0)
        return Autoscaler(kube, "kf", "srv", reg, **kw)

    class _FixedLoad:
        """Registry stand-in: the autoscaler only reads total_load()
        and ready_count()."""

        def __init__(self, load, ready=1):
            self.load = load
            self.ready = ready

        def total_load(self):
            return self.load

        def ready_count(self):
            return self.ready

    def test_scale_up_on_load(self):
        kube = FakeKube()
        self._deployment(kube, 1)
        reg = self._FixedLoad(20.0, ready=1)
        with faults.injected("seed=0"):
            out = self._scaler(kube, reg).reconcile_once()
        assert out["applied"] and out["desired"] == 5
        assert kube.get_deployment("kf", "srv")["spec"]["replicas"] == 5

    def test_hysteresis_holds_inside_tolerance_band(self):
        kube = FakeKube()
        self._deployment(kube, 2)
        # capacity = 8; load 9 is inside the +20% band (9.6): hold.
        reg = self._FixedLoad(9.0, ready=2)
        with faults.injected("seed=0"):
            out = self._scaler(kube, reg, tolerance=0.2).reconcile_once()
        assert not out["applied"] and out["desired"] == 2

    def test_scale_up_cooldown_gates_consecutive_ups(self):
        kube = FakeKube()
        self._deployment(kube, 1)
        reg = self._FixedLoad(9.0, ready=1)
        with faults.injected("seed=0") as inj:
            scaler = self._scaler(kube, reg)
            assert scaler.reconcile_once()["applied"]
            reg.load = 30.0
            out = scaler.reconcile_once()  # inside cooldown: held
            assert not out["applied"]
            inj.advance_clock(11)
            out = scaler.reconcile_once()
            assert out["applied"] and out["desired"] == 8  # max bound

    def test_scale_down_waits_longer_cooldown(self):
        kube = FakeKube()
        self._deployment(kube, 4)
        reg = self._FixedLoad(2.0, ready=4)
        with faults.injected("seed=0") as inj:
            scaler = self._scaler(kube, reg)
            scaler._last_scale_t = faults.monotonic()
            assert not scaler.reconcile_once()["applied"]
            inj.advance_clock(11)  # past up-cooldown, not down
            assert not scaler.reconcile_once()["applied"]
            inj.advance_clock(60)
            out = scaler.reconcile_once()
            assert out["applied"] and out["desired"] == 1

    def test_min_bound_holds_at_zero_load(self):
        kube = FakeKube()
        self._deployment(kube, 3)
        reg = self._FixedLoad(0.0, ready=3)
        with faults.injected("seed=0") as inj:
            scaler = self._scaler(kube, reg, min_replicas=2)
            inj.advance_clock(120)
            out = scaler.reconcile_once()
        assert out["desired"] == 2
        assert kube.get_deployment("kf", "srv")["spec"]["replicas"] == 2

    def test_scale_to_zero_supported_when_min_is_zero(self):
        # Regression: the scale-down band guard degenerated to
        # 0 >= 0 at current == 1, pinning a min_replicas=0 fleet at
        # one replica forever.
        kube = FakeKube()
        self._deployment(kube, 1)
        reg = self._FixedLoad(0.0, ready=1)
        with faults.injected("seed=0") as inj:
            scaler = self._scaler(kube, reg, min_replicas=0)
            inj.advance_clock(120)
            out = scaler.reconcile_once()
        assert out["applied"] and out["desired"] == 0
        assert kube.get_deployment("kf", "srv")["spec"]["replicas"] == 0

    def test_scale_patch_is_level_triggered_idempotent(self):
        kube = FakeKube()
        self._deployment(kube, 1)
        reg = self._FixedLoad(20.0, ready=1)
        with faults.injected("seed=0") as inj:
            scaler = self._scaler(kube, reg)
            scaler.reconcile_once()
            inj.advance_clock(60)
            out = scaler.reconcile_once()  # same load, same answer
        assert not out["applied"]
        assert kube.get_deployment("kf", "srv")["spec"]["replicas"] == 5


class TestAutoscalerClaims:
    """Colocation mode (scheduler/colocate.py): desire flows into the
    serving claim CR; ``spec.replicas`` belongs to the arbiter's
    reconciler, never to the autoscaler."""

    def _deployment(self, kube, replicas=1):
        kube.create_deployment({
            "metadata": {"namespace": "kf", "name": "srv"},
            "spec": {"replicas": replicas}})

    def _scaler(self, kube, reg, **kw):
        from kubeflow_tpu.scheduler.colocate import ServingClaimClient

        kw.setdefault("claims", ServingClaimClient(kube, "kf", "srv"))
        kw.setdefault("target_inflight_per_replica", 4.0)
        kw.setdefault("min_replicas", 1)
        kw.setdefault("max_replicas", 8)
        return Autoscaler(kube, "kf", "srv", reg, **kw)

    def test_desire_rides_claim_cr_not_spec_replicas(self):
        kube = FakeKube()
        self._deployment(kube, 1)
        reg = TestAutoscaler._FixedLoad(20.0)
        with faults.injected("seed=0"):
            out = self._scaler(kube, reg).reconcile_once()
        assert out["applied"] and out["desired"] == 5
        assert out["claim"]["state"] == "pending"
        cr = kube.get_custom("kf", "serving-srv")
        assert cr["spec"]["numSlices"] == 5
        assert cr["metadata"]["labels"][
            "kubeflow-tpu.org/workload"] == "serving"
        # spec.replicas untouched: the reconciler patches on GRANT.
        assert kube.get_deployment("kf", "srv")["spec"]["replicas"] == 1

    def test_scale_to_zero_releases_whole_claim(self):
        from kubeflow_tpu.operator.kube import NotFound

        kube = FakeKube()
        self._deployment(kube, 2)
        reg = TestAutoscaler._FixedLoad(8.0)
        with faults.injected("seed=0") as inj:
            scaler = self._scaler(kube, reg, min_replicas=0)
            scaler.reconcile_once()
            assert kube.get_custom("kf", "serving-srv")
            reg.load = 0.0
            inj.advance_clock(120)
            out = scaler.reconcile_once()
        assert out["desired"] == 0
        assert out["claim"]["state"] == "released"
        # The trough hands every chip back: claim CR gone, and the
        # deployment zeroed directly (release needs no arbitration).
        with pytest.raises(NotFound):
            kube.get_custom("kf", "serving-srv")
        assert kube.get_deployment("kf", "srv")["spec"]["replicas"] == 0

    def test_hysteresis_band_never_flaps_claim(self):
        """Load wobbling inside the tolerance band must not churn the
        claim CR (each churn is a delete+create the arbiter re-plans)
        nor mint scale events."""
        from kubeflow_tpu.runtime.prom import (
            REGISTRY,
            parse_metrics,
            sample_value,
        )

        class CountingKube(FakeKube):
            creates = 0

            def create_custom(self, cr):
                self.creates += 1
                return super().create_custom(cr)

        kube = CountingKube()
        self._deployment(kube, 2)
        reg = TestAutoscaler._FixedLoad(8.0, ready=2)
        with faults.injected("seed=0") as inj:
            scaler = self._scaler(kube, reg, tolerance=0.2)
            scaler.reconcile_once()   # steady state: claim at 2
            assert kube.creates == 1
            before = sample_value(
                parse_metrics(REGISTRY.render()),
                "kft_autoscaler_scale_events_total", direction="up")
            for load in (9.0, 7.0, 9.5, 6.5):   # inside the band
                reg.load = load
                inj.advance_clock(120)   # cooldowns can't be the gate
                out = scaler.reconcile_once()
                assert not out["applied"]
        assert kube.creates == 1   # synced every pass, churned never
        assert kube.get_custom("kf", "serving-srv")[
            "spec"]["numSlices"] == 2
        after = sample_value(
            parse_metrics(REGISTRY.render()),
            "kft_autoscaler_scale_events_total", direction="up")
        assert after == before

    def test_denied_claim_reported_and_counted(self):
        from kubeflow_tpu.runtime.prom import (
            REGISTRY,
            parse_metrics,
            sample_value,
        )

        kube = FakeKube()
        self._deployment(kube, 1)
        reg = TestAutoscaler._FixedLoad(20.0)
        with faults.injected("seed=0") as inj:
            scaler = self._scaler(kube, reg)
            scaler.reconcile_once()
            # The arbiter's verdict comes back on the claim status.
            kube.update_custom_status(
                "kf", "serving-srv",
                {"grantedReplicas": 0, "denied": True})
            inj.advance_clock(11)   # past the up-cooldown: desire holds
            out = scaler.reconcile_once()
        assert out["claim"]["state"] == "denied"
        assert kube.get_deployment("kf", "srv")["spec"]["replicas"] == 1
        assert sample_value(
            parse_metrics(REGISTRY.render()),
            "kft_autoscaler_claim_denied_total", deployment="srv") >= 1

    def test_no_colocation_flag_restores_legacy_direct_patch(self):
        """--no-colocation (fleet/main.py) builds no claim client;
        claims=None is the legacy path — the autoscaler patches
        spec.replicas itself."""
        from kubeflow_tpu.fleet.main import build_parser

        args = build_parser().parse_args(["--no-colocation"])
        assert args.no_colocation is True
        assert build_parser().parse_args([]).no_colocation is False
        kube = FakeKube()
        self._deployment(kube, 1)
        reg = TestAutoscaler._FixedLoad(20.0)
        with faults.injected("seed=0"):
            out = self._scaler(kube, reg, claims=None).reconcile_once()
        assert out["applied"] and "claim" not in out
        assert kube.get_deployment("kf", "srv")["spec"]["replicas"] == 5
        assert not kube.list_custom()


class TestSnapshotLockDiscipline:
    """PR-8 lock-guard audit regressions: every field a status/stats
    snapshot reads must be read under the same lock the writer holds
    (the analyzer catches bare WRITES; these pin the read side)."""

    def _state(self):
        from kubeflow_tpu.fleet.endpoints import (
            EndpointState,
            _EjectBreaker,
        )

        reg = EndpointRegistry(StaticEndpoints([]))
        state = EndpointState(Endpoint(name="r0", url=""), 3,
                              _EjectBreaker())
        state.ready = True
        reg._states["r0"] = state
        return reg, state

    def test_total_load_never_reads_torn_scrape_pairs(self):
        """A scrape writes (inflight, queue_depth) atomically under
        the state lock with a constant sum; total_load() must never
        observe a mixture of two scrapes.  Pre-fix (bare reads) this
        flaked; the locked read makes it deterministic."""
        reg, state = self._state()
        stop = threading.Event()

        def scraper():
            flip = 0.0
            while not stop.is_set():
                flip = 100.0 - flip
                with state._lock:
                    state.inflight = flip
                    state.queue_depth = 100.0 - flip

        t = threading.Thread(target=scraper, daemon=True)
        t.start()
        try:
            for _ in range(3000):
                assert reg.total_load() == 100.0
        finally:
            stop.set()
            t.join(timeout=5)

    def test_describe_reads_breaker_failures_via_locked_accessor(self):
        """describe() must go through _EjectBreaker.failure_count()
        (locked), not the bare attribute — the breaker mutates
        failures under its own lock on every probe verdict."""
        reg, state = self._state()
        state.breaker.failure_count = lambda: 777
        rows = reg.describe()
        assert rows[0]["breaker_failures"] == 777
