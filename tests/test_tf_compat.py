"""TF-Serving Predict wire compatibility (VERDICT r4 item 9).

The clone protos must parse bytes the REAL tensorflow produces and
produce bytes the real tensorflow parses — both directions are
cross-validated against the installed tensorflow's tensor_pb2 /
make_tensor_proto / make_ndarray, and the end-to-end test drives the
live gRPC server through /tensorflow.serving.PredictionService/Predict
with a reference-shaped request (raw JPEG bytes in a DT_STRING tensor,
the inception-client/label.py contract).
"""

import io

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")

from tensorflow.core.framework import tensor_pb2 as _real_tensor_pb2  # noqa: E402
from kubeflow_tpu.serving import tf_compat  # noqa: E402
from kubeflow_tpu.serving.protos import tf_compat_pb2 as pb  # noqa: E402


class TestTensorProtoWireCompat:
    @pytest.mark.parametrize("arr", [
        np.arange(12, dtype=np.float32).reshape(3, 4),
        np.arange(6, dtype=np.int64).reshape(2, 3),
        (np.arange(24) % 255).astype(np.uint8).reshape(2, 3, 4),
        np.asarray([[True, False]]),
    ])
    def test_parses_real_tf_tensorproto(self, arr):
        real = tf.make_tensor_proto(arr)
        clone = pb.TensorProto.FromString(real.SerializeToString())
        out = tf_compat.tensorproto_to_numpy(clone)
        assert out.dtype == arr.dtype
        np.testing.assert_array_equal(out, arr)

    def test_parses_small_tensor_val_fields(self):
        # make_tensor_proto uses float_val (not tensor_content) for
        # tiny tensors — the other client encoding.
        real = tf.make_tensor_proto(3.5, shape=[2, 2])
        clone = pb.TensorProto.FromString(real.SerializeToString())
        out = tf_compat.tensorproto_to_numpy(clone)
        np.testing.assert_array_equal(out, np.full((2, 2), 3.5, np.float32))

    def test_decoded_arrays_are_writable(self):
        """Both request encodings must hand predict a WRITABLE array:
        frombuffer over tensor_content (and broadcast_to on the
        one-value shorthand) view read-only memory, and an in-place
        normalize/pad downstream would raise only for those payloads
        — a payload-dependent failure mode (ADVICE r5)."""
        arr = np.arange(12, dtype=np.float32).reshape(3, 4)
        packed = pb.TensorProto.FromString(
            tf.make_tensor_proto(arr).SerializeToString())
        out = tf_compat.tensorproto_to_numpy(packed)
        assert out.flags.writeable
        out *= 2.0  # the in-place op that used to raise
        broadcast = pb.TensorProto.FromString(
            tf.make_tensor_proto(3.5, shape=[4]).SerializeToString())
        out = tf_compat.tensorproto_to_numpy(broadcast)
        assert out.flags.writeable
        out += 1.0

    def test_parses_string_tensor(self):
        blobs = [b"raw-jpeg-1", b"raw-jpeg-2"]
        real = tf.make_tensor_proto(blobs, shape=[2])
        clone = pb.TensorProto.FromString(real.SerializeToString())
        assert tf_compat.tensorproto_to_numpy(clone) == blobs

    def test_real_tf_parses_our_response_tensors(self):
        arr = np.linspace(0, 1, 10, dtype=np.float32).reshape(2, 5)
        ours = tf_compat.numpy_to_tensorproto(arr)
        real = _real_tensor_pb2.TensorProto.FromString(
            ours.SerializeToString())
        np.testing.assert_array_equal(tf.make_ndarray(real), arr)

    def test_request_wrapper_round_trips_model_spec(self):
        req = pb.PredictRequest()
        req.model_spec.name = "inception"
        req.model_spec.signature_name = "predict_images"
        req.model_spec.version.value = 7
        back = pb.PredictRequest.FromString(req.SerializeToString())
        assert back.model_spec.name == "inception"
        assert back.model_spec.version.value == 7


class TestImageDecode:
    def _jpeg(self, rng, size=32):
        from PIL import Image

        img = Image.fromarray(
            rng.randint(0, 255, (size, size, 3), dtype=np.uint8))
        buf = io.BytesIO()
        img.save(buf, format="JPEG")
        return buf.getvalue()

    def test_decode_image_bytes(self):
        rng = np.random.RandomState(0)
        batch = tf_compat.decode_image_bytes(
            [self._jpeg(rng), self._jpeg(rng)])
        assert batch.shape == (2, 32, 32, 3)
        assert batch.dtype == np.uint8

    def test_images_key_aliased_and_decoded(self):
        rng = np.random.RandomState(1)
        req = pb.PredictRequest()
        real = tf.make_tensor_proto([self._jpeg(rng)], shape=[1])
        req.inputs["images"].ParseFromString(real.SerializeToString())
        inputs = tf_compat.request_inputs_to_numpy(req)
        assert set(inputs) == {"image"}
        assert inputs["image"].shape == (1, 32, 32, 3)


class TestEndToEndReferenceShapedPredict:
    def test_reference_client_request_runs_unchanged(self, tmp_path):
        """A byte-identical reference-era request (DT_STRING raw JPEG,
        inputs['images'], signature predict_images) served end to end
        through the live gRPC port."""
        import grpc
        import jax

        from kubeflow_tpu.models.resnet import ResNetConfig
        from kubeflow_tpu.serving.export import export
        from kubeflow_tpu.serving.grpc_server import make_grpc_server
        from kubeflow_tpu.serving.model_server import ModelServer

        rng = np.random.RandomState(2)
        # Same construction the classifier loader will use at load time
        # (family + num_classes + num_filters), or shapes mismatch.
        model = ResNetConfig._FACTORIES["resnet18"](
            num_classes=10, num_filters=8)
        variables = model.init(
            jax.random.key(0), np.zeros((1, 32, 32, 3), np.float32),
            train=False)
        export(str(tmp_path / "m"), 1, variables,
               loader="kubeflow_tpu.serving.loaders:classifier",
               config={"family": "resnet18", "num_classes": 10,
                       "num_filters": 8})
        server = ModelServer()
        server.add_model("inception", str(tmp_path / "m"))
        grpc_srv = make_grpc_server(server, port=0, host="127.0.0.1")
        try:
            req = pb.PredictRequest()
            req.model_spec.name = "inception"
            req.model_spec.signature_name = "predict_images"
            jpeg = TestImageDecode()._jpeg(rng)
            req.inputs["images"].ParseFromString(
                tf.make_tensor_proto([jpeg], shape=[1])
                .SerializeToString())

            channel = grpc.insecure_channel(
                f"127.0.0.1:{grpc_srv.bound_port}")
            call = channel.unary_unary(
                "/tensorflow.serving.PredictionService/Predict",
                request_serializer=pb.PredictRequest.SerializeToString,
                response_deserializer=pb.PredictResponse.FromString,
            )
            resp = call(req, timeout=120)
            scores = tf_compat.tensorproto_to_numpy(
                resp.outputs["scores"])
            assert scores.shape == (1, 10)
            np.testing.assert_allclose(scores.sum(), 1.0, atol=1e-3)
            assert resp.model_spec.version.value == 1
            # The real tensorflow can parse our response tensor too.
            real = _real_tensor_pb2.TensorProto.FromString(
                resp.outputs["scores"].SerializeToString())
            np.testing.assert_array_equal(tf.make_ndarray(real), scores)
            channel.close()
        finally:
            grpc_srv.stop(grace=None)
