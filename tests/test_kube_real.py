"""RealKube adapter + operator daemon entrypoint, against a stubbed
``kubernetes`` client.

The reference could only prove its operator deployment on rented clusters
(/root/reference/testing/test_deploy.py:160-190 deploy-then-verify); here
the production adapter's 1:1 method mapping — create/list/delete, label
selectors, CRD group/version routing, 404/409 translation — is verified
hermetically by injecting a fake ``kubernetes`` module.
"""

from __future__ import annotations

import sys
import types
from typing import Any, Dict, List, Optional

import pytest

from kubeflow_tpu.operator import crd
from kubeflow_tpu.operator.kube import Conflict, NotFound


class ApiException(Exception):
    def __init__(self, status: int, reason: str = ""):
        super().__init__(f"{status}: {reason}")
        self.status = status


class _Obj:
    """Mimics the kubernetes client's model objects (sanitizable)."""

    def __init__(self, data):
        self.data = data


class FakeCoreV1Api:
    """Records calls; raises ApiException(404/409) on demand."""

    def __init__(self, state):
        self.state = state
        self.api_client = types.SimpleNamespace(
            sanitize_for_serialization=lambda o: o.data
            if isinstance(o, _Obj) else o
        )

    # pods
    def create_namespaced_pod(self, namespace, pod):
        key = (namespace, pod["metadata"]["name"])
        if key in self.state["pods"]:
            raise ApiException(409, "exists")
        pod = dict(pod)
        pod.setdefault("status", {"phase": "Pending"})  # apiserver adds this
        self.state["pods"][key] = pod
        return pod

    def read_namespaced_pod(self, name, namespace):
        try:
            return _Obj(self.state["pods"][(namespace, name)])
        except KeyError:
            raise ApiException(404, "nope") from None

    def list_namespaced_pod(self, namespace, label_selector=None):
        items = []
        want = dict(
            pair.split("=", 1) for pair in (label_selector or "").split(",")
            if pair
        )
        for (ns, _), pod in self.state["pods"].items():
            if ns != namespace:
                continue
            labels = pod["metadata"].get("labels", {})
            if all(labels.get(k) == v for k, v in want.items()):
                items.append(_Obj(pod))
        self.state["last_selector"] = label_selector
        return types.SimpleNamespace(items=items)

    def delete_namespaced_pod(self, name, namespace):
        try:
            del self.state["pods"][(namespace, name)]
        except KeyError:
            raise ApiException(404, "nope") from None

    # services
    def create_namespaced_service(self, namespace, svc):
        key = (namespace, svc["metadata"]["name"])
        if key in self.state["services"]:
            raise ApiException(409, "exists")
        self.state["services"][key] = svc
        return svc

    def delete_namespaced_service(self, name, namespace):
        try:
            del self.state["services"][(namespace, name)]
        except KeyError:
            raise ApiException(404, "nope") from None

    # events
    def create_namespaced_event(self, namespace, event):
        self.state["events"].append((namespace, event))
        return event


class FakeCustomObjectsApi:
    def __init__(self, state):
        self.state = state

    def _check(self, group, version, plural):
        assert group == crd.GROUP and version == crd.VERSION
        assert plural == crd.PLURAL

    def list_namespaced_custom_object(self, group, version, namespace,
                                      plural):
        self._check(group, version, plural)
        return {"items": [o for (ns, _), o in self.state["custom"].items()
                          if ns == namespace]}

    def list_cluster_custom_object(self, group, version, plural):
        self._check(group, version, plural)
        return {"items": list(self.state["custom"].values())}

    def get_namespaced_custom_object(self, group, version, namespace,
                                     plural, name):
        self._check(group, version, plural)
        try:
            return self.state["custom"][(namespace, name)]
        except KeyError:
            raise ApiException(404, "nope") from None

    def patch_namespaced_custom_object_status(self, group, version,
                                              namespace, plural, name, body):
        self._check(group, version, plural)
        try:
            self.state["custom"][(namespace, name)]["status"] = body["status"]
        except KeyError:
            raise ApiException(404, "nope") from None

    def delete_namespaced_custom_object(self, group, version, namespace,
                                        plural, name):
        self._check(group, version, plural)
        try:
            del self.state["custom"][(namespace, name)]
        except KeyError:
            raise ApiException(404, "nope") from None


class FakeAppsV1Api:
    """apps/v1 slice: deployments (the fleet autoscaler's target)."""

    def __init__(self, state):
        self.state = state

    def create_namespaced_deployment(self, namespace, body):
        key = (namespace, body["metadata"]["name"])
        if key in self.state["deployments"]:
            raise ApiException(409, "exists")
        self.state["deployments"][key] = body
        return body

    def read_namespaced_deployment(self, name, namespace):
        try:
            return self.state["deployments"][(namespace, name)]
        except KeyError:
            raise ApiException(404, "nope") from None

    def list_namespaced_deployment(self, namespace, label_selector=None):
        items = [d for (ns, _), d in self.state["deployments"].items()
                 if ns == namespace]
        return types.SimpleNamespace(items=items)

    def patch_namespaced_deployment(self, name, namespace, body):
        try:
            dep = self.state["deployments"][(namespace, name)]
        except KeyError:
            raise ApiException(404, "nope") from None
        dep.setdefault("spec", {}).update(body.get("spec", {}))
        return dep


@pytest.fixture()
def fake_kubernetes(monkeypatch):
    """Inject a minimal ``kubernetes`` module into sys.modules."""
    state: Dict[str, Any] = {"pods": {}, "services": {}, "custom": {},
                             "deployments": {}, "events": [],
                             "incluster": False}

    mod = types.ModuleType("kubernetes")
    config = types.SimpleNamespace()

    def load_incluster_config():
        if not state["incluster"]:
            raise RuntimeError("not in cluster")

    def load_kube_config(config_file=None):
        state["kubeconfig"] = config_file

    config.load_incluster_config = load_incluster_config
    config.load_kube_config = load_kube_config

    client = types.SimpleNamespace(
        CoreV1Api=lambda: FakeCoreV1Api(state),
        AppsV1Api=lambda: FakeAppsV1Api(state),
        CustomObjectsApi=lambda: FakeCustomObjectsApi(state),
        rest=types.SimpleNamespace(ApiException=ApiException),
    )
    mod.config = config
    mod.client = client
    monkeypatch.setitem(sys.modules, "kubernetes", mod)
    return state


@pytest.fixture()
def real_kube(fake_kubernetes):
    from kubeflow_tpu.operator.kube_real import RealKube

    return RealKube(kubeconfig="/tmp/kc"), fake_kubernetes


def make_pod(name="p0", ns="kubeflow", labels=None):
    return {"apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": name, "namespace": ns,
                         "labels": labels or {}},
            "spec": {}, "status": {"phase": "Pending"}}


class TestRealKubePods:
    def test_create_get_delete(self, real_kube):
        rk, state = real_kube
        rk.create_pod(make_pod())
        assert ("kubeflow", "p0") in state["pods"]
        got = rk.get_pod("kubeflow", "p0")
        assert got["metadata"]["name"] == "p0"
        rk.delete_pod("kubeflow", "p0")
        assert ("kubeflow", "p0") not in state["pods"]

    def test_conflict_and_notfound_translation(self, real_kube):
        rk, _ = real_kube
        rk.create_pod(make_pod())
        with pytest.raises(Conflict):
            rk.create_pod(make_pod())
        with pytest.raises(NotFound):
            rk.get_pod("kubeflow", "missing")
        with pytest.raises(NotFound):
            rk.delete_pod("kubeflow", "missing")

    def test_list_pods_label_selector(self, real_kube):
        rk, state = real_kube
        rk.create_pod(make_pod("a", labels={"job": "x", "idx": "0"}))
        rk.create_pod(make_pod("b", labels={"job": "y"}))
        out = rk.list_pods("kubeflow", labels={"job": "x"})
        assert [p["metadata"]["name"] for p in out] == ["a"]
        assert "job=x" in state["last_selector"]
        # No labels -> no selector sent.
        rk.list_pods("kubeflow")
        assert state["last_selector"] is None


class TestRealKubeDeployments:
    def test_deployment_crud_and_scale(self, real_kube):
        rk, state = real_kube
        rk.create_deployment({
            "metadata": {"name": "srv", "namespace": "kubeflow"},
            "spec": {"replicas": 1}})
        assert ("kubeflow", "srv") in state["deployments"]
        assert rk.get_deployment(
            "kubeflow", "srv")["spec"]["replicas"] == 1
        assert len(rk.list_deployments("kubeflow")) == 1
        rk.patch_deployment_scale("kubeflow", "srv", 4)
        assert state["deployments"][
            ("kubeflow", "srv")]["spec"]["replicas"] == 4
        from kubeflow_tpu.operator.kube import NotFound

        with pytest.raises(NotFound):
            rk.patch_deployment_scale("kubeflow", "ghost", 2)


class TestRealKubeServicesAndCustom:
    def test_service_roundtrip(self, real_kube):
        rk, state = real_kube
        svc = {"metadata": {"name": "s", "namespace": "kubeflow"}}
        rk.create_service(svc)
        assert ("kubeflow", "s") in state["services"]
        with pytest.raises(Conflict):
            rk.create_service(svc)
        rk.delete_service("kubeflow", "s")
        with pytest.raises(NotFound):
            rk.delete_service("kubeflow", "s")

    def test_custom_crud_and_status(self, real_kube):
        rk, state = real_kube
        cr = crd.TPUJobSpec(name="train").to_custom_resource()
        ns = cr["metadata"]["namespace"]
        state["custom"][(ns, "train")] = cr
        assert rk.get_custom(ns, "train")["metadata"]["name"] == "train"
        assert len(rk.list_custom()) == 1
        assert len(rk.list_custom(namespace=ns)) == 1
        assert rk.list_custom(namespace="elsewhere") == []
        rk.update_custom_status(ns, "train", {"phase": "Running"})
        assert state["custom"][(ns, "train")]["status"]["phase"] == "Running"
        rk.delete_custom(ns, "train")
        assert not state["custom"]
        with pytest.raises(NotFound):
            rk.get_custom(ns, "train")

    def test_events_best_effort(self, real_kube):
        rk, state = real_kube
        rk.record_event("kubeflow", "TPUJob/train", "Started", "gang up")
        assert state["events"]
        ns, ev = state["events"][0]
        assert ev["involvedObject"]["kind"] == "TPUJob"
        assert ev["reason"] == "Started"

    def test_incluster_config_preferred(self, fake_kubernetes):
        from kubeflow_tpu.operator.kube_real import RealKube

        fake_kubernetes["incluster"] = True
        fake_kubernetes["kubeconfig"] = "UNTOUCHED"
        RealKube()
        assert fake_kubernetes["kubeconfig"] == "UNTOUCHED"


class TestOperatorMain:
    def test_parse_inventory(self):
        from kubeflow_tpu.operator.main import parse_inventory

        assert parse_inventory(["v5e-8=4", "v5p-32=2"]) == {
            "v5e-8": 4, "v5p-32": 2}
        assert parse_inventory(["v5e-8"]) == {"v5e-8": 1}

    def test_fake_kube_loop_runs(self):
        from kubeflow_tpu.operator.main import main

        rc = main(["--fake-kube", "--max-iterations", "2",
                   "--poll-interval-s", "0", "--inventory", "v5e-8=1"])
        assert rc == 0

    def test_real_kube_drives_reconciler(self, fake_kubernetes, monkeypatch):
        """operator/main.py end-to-end against the stubbed client: a CR in
        the fake API server reaches Starting with pods created."""
        from kubeflow_tpu.operator.main import main

        cr = crd.TPUJobSpec(name="train", slice_type="v5e-8").to_custom_resource()
        ns = cr["metadata"]["namespace"]
        fake_kubernetes["custom"][(ns, "train")] = cr
        fake_kubernetes["incluster"] = True
        rc = main(["--max-iterations", "2", "--poll-interval-s", "0",
                   "--inventory", "v5e-8=2"])
        assert rc == 0
        assert cr["status"]["phase"] == "Starting"
        names = sorted(n for (_, n) in fake_kubernetes["pods"])
        assert names and all(n.startswith("train-worker-") for n in names)
        assert (ns, "train") in fake_kubernetes["services"]

    def test_no_cluster_access_errors(self, monkeypatch):
        from kubeflow_tpu.operator.main import main

        monkeypatch.setitem(sys.modules, "kubernetes", None)
        rc = main(["--max-iterations", "1"])
        assert rc == 1
