"""Flagship Transformer tests: shapes, causality, sharded training on a
dp x tp mesh, GQA, remat equivalence."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from flax import linen as nn

from kubeflow_tpu.models.transformer import (
    Transformer,
    TransformerConfig,
    lm_task,
)
from kubeflow_tpu.parallel import DEFAULT_RULES, MeshSpec, TENSOR
from kubeflow_tpu.runtime.metrics import MetricsLogger
from kubeflow_tpu.runtime.train import Trainer

CFG = TransformerConfig(
    vocab_size=64, d_model=32, n_layers=2, n_heads=4, n_kv_heads=2,
    d_ff=64, head_dim=8, max_seq_len=32,
)


def _init(cfg=CFG, seed=0, seq=16):
    model = Transformer(cfg)
    toks = jnp.zeros((2, seq), jnp.int32)
    return model, model.init(jax.random.key(seed), toks)


class TestForward:
    def test_logits_shape_dtype(self):
        model, vars_ = _init()
        toks = jnp.ones((2, 16), jnp.int32)
        logits = model.apply(vars_, toks)
        assert logits.shape == (2, 16, CFG.vocab_size)
        assert logits.dtype == jnp.float32

    def test_causality(self):
        """Changing a future token must not affect earlier logits."""
        model, vars_ = _init()
        rng = np.random.RandomState(0)
        toks = rng.randint(0, CFG.vocab_size, (1, 16)).astype(np.int32)
        toks2 = toks.copy()
        toks2[0, -1] = (toks2[0, -1] + 1) % CFG.vocab_size
        l1 = model.apply(vars_, jnp.asarray(toks))
        l2 = model.apply(vars_, jnp.asarray(toks2))
        np.testing.assert_allclose(
            np.asarray(l1[0, :-1]), np.asarray(l2[0, :-1]), atol=1e-5
        )
        assert not np.allclose(np.asarray(l1[0, -1]), np.asarray(l2[0, -1]))

    def test_scan_stacks_layer_params(self):
        _, vars_ = _init()
        wq = vars_["params"]["layers"]["attn"]["wq"]
        assert nn.unbox(wq).shape == (CFG.n_layers, CFG.d_model, CFG.n_heads,
                                      CFG.head_dim)

    def test_remat_matches_baseline(self):
        cfg_r = TransformerConfig(**{**CFG.__dict__, "remat": True})
        model, vars_ = _init()
        model_r = Transformer(cfg_r)
        toks = jnp.ones((1, 8), jnp.int32)
        np.testing.assert_allclose(
            np.asarray(model.apply(vars_, toks)),
            np.asarray(model_r.apply(vars_, toks)),
            atol=1e-5,
        )

    def test_remat_minimal_policy_grads_match(self):
        """The long-context `minimal` policy (save nothing, recompute
        every matmul in the bwd) must change memory only — grads match
        the default policy's."""
        from kubeflow_tpu.models.transformer import lm_task

        toks = jnp.asarray(
            np.arange(2 * 8, dtype=np.int32).reshape(2, 8)
            % CFG.vocab_size)
        rng = jax.random.key(1)
        grads = {}
        for policy in ("nobatch", "minimal"):
            cfg = TransformerConfig(
                **{**CFG.__dict__, "remat": True, "remat_policy": policy})
            init_fn, loss_fn = lm_task(cfg)
            params, mutable = init_fn(jax.random.key(0))
            g = jax.grad(
                lambda p: loss_fn(p, mutable, {"tokens": toks}, rng)[0]
            )(params)
            grads[policy] = [
                np.asarray(x) for x in jax.tree.leaves(nn.unbox(g))]
        assert grads["nobatch"] and (
            len(grads["nobatch"]) == len(grads["minimal"]))
        for a, b in zip(grads["nobatch"], grads["minimal"]):
            np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-6)


class TestShardedTraining:
    def test_tp_sharded_params_and_loss_decreases(self, devices):
        mesh = MeshSpec(data=2, fsdp=2, tensor=2).build(devices)
        init_fn, loss_fn = lm_task(CFG)
        tr = Trainer(
            init_fn=init_fn, loss_fn=loss_fn, tx=optax.adam(3e-3), mesh=mesh,
            metrics=MetricsLogger(stream=open("/dev/null", "w")),
        )
        state = tr.create_state()
        # MLP wi kernel [2, layers?, embed, ff]: ff dim sharded over tensor.
        wi = state.params["layers"]["mlp"]["wi"]
        spec = tuple(wi.sharding.spec)
        assert TENSOR in spec and "fsdp" in spec, spec

        rng = np.random.RandomState(0)

        def data():
            while True:
                # Learnable structure: token t follows t (copy-ish stream).
                start = rng.randint(0, 8, size=(8, 1))
                toks = (start + np.arange(16)[None, :]) % 16
                yield {"tokens": toks.astype(np.int32)}

        state = tr.fit(data(), num_steps=30, examples_per_step=8, log_every=0)
        assert tr._last_metrics["loss"] < 2.0, tr._last_metrics

    def test_gqa_fewer_kv_heads(self):
        model, vars_ = _init()
        n_q = nn.unbox(vars_["params"]["layers"]["attn"]["wq"]).shape[2]
        n_kv = nn.unbox(vars_["params"]["layers"]["attn"]["wkv"]).shape[3]
        assert (n_q, n_kv) == (4, 2)


class TestPipelineParallel:
    """TransformerConfig.pipeline_microbatches: the REAL block through the
    GPipe schedule (VERDICT r3 item 2 — previously a toy-MLP-only
    primitive)."""

    PP_CFG = TransformerConfig(
        **{**CFG.__dict__, "n_layers": 2, "pipeline_microbatches": 4})

    def test_pipelined_logits_match_sequential(self, devices):
        """Same params, same tokens: GPipe output == plain nn.scan output,
        composed with dp and tp auto axes on one mesh."""
        mesh = MeshSpec(data=2, pipeline=2, tensor=2).build(devices)
        plain, vars_ = _init(CFG)
        piped = Transformer(self.PP_CFG, mesh=mesh)
        rng = np.random.RandomState(2)
        toks = jnp.asarray(rng.randint(0, CFG.vocab_size, (8, 16)), jnp.int32)
        ref = plain.apply(vars_, toks)
        with mesh, nn.logical_axis_rules(list(DEFAULT_RULES)):
            out = jax.jit(
                lambda v, t: piped.apply(v, t))(nn.unbox(vars_), toks)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-2, rtol=1e-2)

    def test_lm_trains_through_pipeline(self, devices):
        """The flagship LM trains to decreasing loss with pipeline=2 —
        the CRD's workload is the real model, not a tanh toy."""
        mesh = MeshSpec(data=2, pipeline=2, tensor=2).build(devices)
        init_fn, loss_fn = lm_task(self.PP_CFG, mesh=mesh)
        tr = Trainer(
            init_fn=init_fn, loss_fn=loss_fn, tx=optax.adam(3e-3), mesh=mesh,
            metrics=MetricsLogger(stream=open("/dev/null", "w")),
        )
        state = tr.create_state()
        # The layer stack is sharded over the pipeline axis (L/S per stage).
        wq = state.params["layers"]["attn"]["wq"]
        assert "pipeline" in tuple(wq.sharding.spec), wq.sharding.spec

        rng = np.random.RandomState(0)
        first = None

        def data():
            while True:
                start = rng.randint(0, 8, size=(8, 1))
                toks = (start + np.arange(16)[None, :]) % 16
                yield {"tokens": toks.astype(np.int32)}

        it = data()
        state = tr.fit(it, num_steps=1, examples_per_step=8, log_every=0)
        first = tr._last_metrics["loss"]
        state = tr.fit(it, num_steps=30, state=state, examples_per_step=8,
                       log_every=0)
        assert tr._last_metrics["loss"] < first, (
            first, tr._last_metrics["loss"])
        assert tr._last_metrics["loss"] < 2.0, tr._last_metrics

    def test_remat_pipelined_matches(self, devices):
        mesh = MeshSpec(data=1, pipeline=2).build(devices[:2])
        cfg_r = TransformerConfig(
            **{**self.PP_CFG.__dict__, "remat": True})
        plain, vars_ = _init(CFG)
        piped = Transformer(cfg_r, mesh=mesh)
        toks = jnp.ones((4, 16), jnp.int32)
        ref = plain.apply(vars_, toks)
        with mesh:
            out = jax.jit(
                lambda v, t: piped.apply(v, t))(nn.unbox(vars_), toks)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-2, rtol=1e-2)

    def test_invalid_combinations_rejected(self):
        # Dropout is the ONE residual wall (rngs are not threaded through
        # the GPipe functional body; every shipped config trains at 0).
        # MoE and ring COMPOSE as of r5 — constructing them must work.
        with pytest.raises(ValueError, match="dropout"):
            TransformerConfig(pipeline_microbatches=2, dropout_rate=0.1)
        TransformerConfig(pipeline_microbatches=2, moe_experts=4)
        TransformerConfig(pipeline_microbatches=2, attention="ring")

    def test_pipelined_moe_matches_microbatched_sequential(self, devices):
        """pp x moe (VERDICT r4 item 3): the sown load-balance aux rides
        the GPipe schedule.  GPipe's semantics ARE per-microbatch: the
        reference is the mean over microbatches of the sequential
        model's loss on that microbatch (equal microbatches make the CE
        part equal full-batch CE, and moe_group_size = tokens-per-
        microbatch aligns the routing groups, so the only differences
        are reduction order)."""
        B, S, M = 8, 16, 4
        base = dict(
            vocab_size=64, d_model=32, n_layers=2, n_heads=4,
            n_kv_heads=2, d_ff=64, head_dim=8, max_seq_len=32,
            dtype=jnp.float32, moe_experts=4,
            moe_group_size=(B // M) * S)
        seq_cfg = TransformerConfig(**base)
        pp_cfg = TransformerConfig(**base, pipeline_microbatches=M)
        mesh = MeshSpec(data=2, pipeline=2, expert=2).build(devices)
        init_seq, loss_seq = lm_task(seq_cfg)
        _, loss_pp = lm_task(pp_cfg, mesh=mesh)
        rng = jax.random.key(0)
        params = init_seq(rng)[0]
        toks = jnp.asarray(
            np.random.RandomState(3).randint(0, 64, (B, S)), jnp.int32)

        def ref_loss(p):
            mbs = toks.reshape(M, B // M, S)
            return sum(loss_seq(p, {}, {"tokens": mbs[m]}, rng)[0]
                       for m in range(M)) / M

        def pp_loss(p):
            return loss_pp(p, {}, {"tokens": toks}, rng)[0]

        with mesh, nn.logical_axis_rules(list(DEFAULT_RULES)):
            l_pp, g_pp = jax.block_until_ready(
                jax.jit(jax.value_and_grad(pp_loss))(params))
        l_ref, g_ref = jax.value_and_grad(ref_loss)(params)
        np.testing.assert_allclose(float(l_pp), float(l_ref), rtol=1e-5)
        for a, b in zip(jax.tree_util.tree_leaves(g_pp),
                        jax.tree_util.tree_leaves(g_ref)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-3)

    def test_pipelined_ring_matches_microbatched_sequential(self, devices):
        """pp x ring (VERDICT r4 item 3): ring attention runs per-shard
        inside the composed {pipeline, sequence}-manual shard_map; ring
        is exact softmax attention, so the pipelined-ring loss and grads
        must match the sequential dot-attention reference."""
        B, S, M = 4, 32, 2
        base = dict(
            vocab_size=64, d_model=32, n_layers=2, n_heads=4,
            n_kv_heads=2, d_ff=64, head_dim=8, max_seq_len=32,
            dtype=jnp.float32)
        seq_cfg = TransformerConfig(**base, attention="dot")
        pp_cfg = TransformerConfig(
            **base, attention="ring", pipeline_microbatches=M)
        mesh = MeshSpec(pipeline=2, sequence=2).build(devices[:4])
        init_seq, loss_seq = lm_task(seq_cfg)
        _, loss_pp = lm_task(pp_cfg, mesh=mesh)
        rng = jax.random.key(0)
        params = init_seq(rng)[0]
        toks = jnp.asarray(
            np.random.RandomState(4).randint(0, 64, (B, S)), jnp.int32)

        def ref_loss(p):
            mbs = toks.reshape(M, B // M, S)
            return sum(loss_seq(p, {}, {"tokens": mbs[m]}, rng)[0]
                       for m in range(M)) / M

        def pp_loss(p):
            return loss_pp(p, {}, {"tokens": toks}, rng)[0]

        with mesh, nn.logical_axis_rules(list(DEFAULT_RULES)):
            l_pp, g_pp = jax.block_until_ready(
                jax.jit(jax.value_and_grad(pp_loss))(params))
        l_ref, g_ref = jax.value_and_grad(ref_loss)(params)
        np.testing.assert_allclose(float(l_pp), float(l_ref), rtol=1e-5)
        for a, b in zip(jax.tree_util.tree_leaves(g_pp),
                        jax.tree_util.tree_leaves(g_ref)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=2e-4, rtol=1e-3)

    def test_pp_ring_moe_all_compose(self, devices):
        """The full stack at once — pipeline x sequence x expert on one
        mesh, ring attention + MoE + GPipe in one program — trains a
        step to a finite loss with the aux metric threaded through."""
        cfg = TransformerConfig(
            vocab_size=64, d_model=32, n_layers=2, n_heads=4,
            n_kv_heads=2, d_ff=64, head_dim=8, max_seq_len=32,
            dtype=jnp.bfloat16, attention="ring",
            pipeline_microbatches=2, moe_experts=2)
        mesh = MeshSpec(pipeline=2, sequence=2, expert=2).build(devices)
        init_fn, loss_fn = lm_task(cfg, mesh=mesh)
        tr = Trainer(
            init_fn=init_fn, loss_fn=loss_fn, tx=optax.adam(1e-3),
            mesh=mesh,
            metrics=MetricsLogger(stream=open("/dev/null", "w")),
        )
        state = tr.create_state()
        step = tr.compile_step()
        toks = np.arange(4 * 32, dtype=np.int32).reshape(4, 32) % 64
        state, metrics = step(state, tr.shard_batch({"tokens": toks}))
        loss = float(jax.block_until_ready(metrics["loss"]))
        assert np.isfinite(loss), loss
        assert float(metrics["moe_aux"]) > 0.0

    def test_indivisible_batch_rejected(self, devices):
        mesh = MeshSpec(data=1, pipeline=2).build(devices[:2])
        cfg = TransformerConfig(
            **{**CFG.__dict__, "n_layers": 2, "pipeline_microbatches": 3})
        model = Transformer(cfg, mesh=mesh)
        vars_ = nn.unbox(model.init(jax.random.key(0),
                                    jnp.zeros((2, 16), jnp.int32)))
        with pytest.raises(ValueError, match="divisible"):
            with mesh:
                model.apply(vars_, jnp.zeros((4, 16), jnp.int32))


class TestFlops:
    def test_flops_positive_and_scales(self):
        small = CFG.flops_per_token()
        big = TransformerConfig(
            **{**CFG.__dict__, "n_layers": 4}
        ).flops_per_token()
        assert 0 < small < big


class TestFusedCE:
    def test_compute_dtype_ce_matches_f32_on_f32_model(self):
        """On a float32 model the two CE paths are numerically
        identical (the flag only changes where casts happen)."""
        rng = np.random.RandomState(5)
        toks = jnp.asarray(rng.randint(0, CFG.vocab_size, (2, 16)),
                           jnp.int32)
        losses = {}
        for mode in ("f32", "compute"):
            cfg = TransformerConfig(
                **{**CFG.__dict__, "dtype": jnp.float32,
                   "ce_dtype": mode})
            init_fn, loss_fn = lm_task(cfg)
            params, _ = init_fn(jax.random.key(0))
            loss, _ = loss_fn(params, {}, {"tokens": toks},
                              jax.random.key(1))
            losses[mode] = float(loss)
        np.testing.assert_allclose(losses["f32"], losses["compute"],
                                   rtol=1e-6)

    @pytest.mark.slow  # ~15s; the f32 compute-dtype identity test stays tier-1
    def test_compute_dtype_ce_close_on_bf16_model(self):
        """bf16 logits with f32-accumulated reductions track the f32
        materialization closely; gradients stay finite."""
        rng = np.random.RandomState(6)
        toks = jnp.asarray(rng.randint(0, CFG.vocab_size, (2, 16)),
                           jnp.int32)
        losses = {}
        for mode in ("f32", "compute"):
            cfg = TransformerConfig(
                **{**CFG.__dict__, "dtype": jnp.bfloat16,
                   "ce_dtype": mode})
            init_fn, loss_fn = lm_task(cfg)
            params, _ = init_fn(jax.random.key(0))

            def scalar_loss(p, loss_fn=loss_fn):
                loss, _ = loss_fn(p, {}, {"tokens": toks},
                                  jax.random.key(1))
                return loss

            loss, grads = jax.value_and_grad(scalar_loss)(params)
            losses[mode] = float(loss)
            finite = jax.tree_util.tree_all(jax.tree_util.tree_map(
                lambda g: bool(np.isfinite(np.asarray(g, np.float32))
                               .all()), grads))
            assert finite
        np.testing.assert_allclose(losses["f32"], losses["compute"],
                                   rtol=5e-3)

    def test_invalid_ce_dtype_rejected(self):
        import pytest

        with pytest.raises(ValueError, match="ce_dtype"):
            TransformerConfig(ce_dtype="fp32")

    @pytest.mark.slow  # ~21s; the f32 compute-dtype identity test stays tier-1
    def test_chunked_ce_matches_unchunked(self):
        """ce_chunk > 0 (no [b, s, vocab] logits in HBM, the seq-128k
        memory lever) must match the unchunked loss AND grads in both
        ce_dtype modes — including a chunk that does not divide s
        (divisor fallback: s=16, ce_chunk=6 -> effective 4).  On an
        f32 model the paths differ only by reassociation; a bf16
        model adds chunk-boundary rounding, covered by the loss-level
        bf16 check in test_compute_dtype_ce_close_on_bf16_model."""
        rng = np.random.RandomState(7)
        toks = jnp.asarray(rng.randint(0, CFG.vocab_size, (2, 16)),
                           jnp.int32)
        for mode in ("f32", "compute"):
            results = {}
            for chunk in (0, 6):
                cfg = TransformerConfig(
                    **{**CFG.__dict__, "dtype": jnp.float32,
                       "ce_dtype": mode, "ce_chunk": chunk})
                init_fn, loss_fn = lm_task(cfg)
                params, _ = init_fn(jax.random.key(0))

                def scalar_loss(p, loss_fn=loss_fn):
                    loss, _ = loss_fn(p, {}, {"tokens": toks},
                                      jax.random.key(1))
                    return loss

                loss, grads = jax.value_and_grad(scalar_loss)(params)
                results[chunk] = (
                    float(loss),
                    [np.asarray(g, np.float32)
                     for g in jax.tree_util.tree_leaves(nn.unbox(grads))])
            np.testing.assert_allclose(
                results[0][0], results[6][0], rtol=1e-6)
            assert results[0][1] and (
                len(results[0][1]) == len(results[6][1]))
            for a, b in zip(results[0][1], results[6][1]):
                np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)
