"""Serving fault-tolerance layer: per-request deadlines, bounded
admission with load shedding, circuit-broken reloads, readiness +
graceful drain, and the typed-error mapping on both wire faces — all
driven deterministically through the fault-injection harness
(kubeflow_tpu/testing/faults.py) instead of wall-clock luck."""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from kubeflow_tpu.serving.errors import (
    BatcherClosed,
    DeadlineExceeded,
    Overloaded,
)
from kubeflow_tpu.serving.model_server import (
    LoadedModel,
    MicroBatcher,
    ModelServer,
    _ReloadBreaker,
)
from kubeflow_tpu.testing import faults

SEED = 20260803
VOCAB, PROMPT_LEN, NEW_TOKENS = 128, 8, 12


class _GatedPredict:
    """predict() that announces entry and blocks until released — the
    deterministic 'wedged device' for queue-behavior tests."""

    def __init__(self):
        self.entered = threading.Event()
        self.release = threading.Event()
        self.calls = 0

    def __call__(self, inputs):
        self.calls += 1
        self.entered.set()
        assert self.release.wait(timeout=30), "test forgot to release"
        return {"y": np.asarray(inputs["x"])}


class TestBatcherDeadlines:
    def test_expired_on_arrival_raises_immediately(self):
        mb = MicroBatcher(lambda i: i, batch_timeout_s=10.0)
        try:
            with pytest.raises(DeadlineExceeded):
                mb.submit({"x": np.zeros((1, 2))},
                          deadline=faults.monotonic() - 0.1)
            assert mb.stats()["deadline_expired"] == 1
        finally:
            mb.close()

    def test_queued_entry_expires_before_batch_window(self):
        """A request deadline preempts the (much longer) batch window:
        the entry is failed at its own deadline, not dispatched 10 s
        later."""
        mb = MicroBatcher(lambda i: {"y": i["x"]}, max_batch_size=4,
                          batch_timeout_s=10.0, name="ft-queue-dl")
        try:
            t0 = time.monotonic()
            with pytest.raises(DeadlineExceeded):
                mb.submit({"x": np.zeros((1, 2))},
                          deadline=faults.monotonic() + 0.1)
            waited = time.monotonic() - t0
            assert waited < 5.0, (
                f"expiry took {waited:.1f}s — the batch window was not "
                "preempted")
            stats = mb.stats()
            assert stats["deadline_expired"] == 1
            assert stats["queue_depth"] == 0
        finally:
            mb.close()

    def test_unexpired_entries_unaffected_by_sweep(self):
        mb = MicroBatcher(lambda i: {"y": np.asarray(i["x"]) * 2},
                          max_batch_size=2, batch_timeout_s=0.02)
        try:
            out = mb.submit({"x": np.ones((1, 2))},
                            deadline=faults.monotonic() + 30.0)
            np.testing.assert_allclose(out["y"], 2 * np.ones((1, 2)))
            assert mb.stats()["deadline_expired"] == 0
        finally:
            mb.close()


class TestBatcherOverload:
    def test_queue_cap_sheds_with_retry_after(self):
        gate = _GatedPredict()
        mb = MicroBatcher(gate, max_batch_size=1, batch_timeout_s=0.001,
                          allowed_batch_sizes=[1], in_flight=1,
                          max_queue_depth=1, overload_retry_after_s=2.5,
                          name="ft-shed")
        results = {}

        def worker(i):
            try:
                results[i] = mb.submit({"x": np.full((1, 1), float(i))})
            except Exception as exc:  # noqa: BLE001 — the point
                results[i] = exc

        try:
            t_a = threading.Thread(target=worker, args=(0,))
            t_a.start()
            assert gate.entered.wait(timeout=10)  # A is IN the device
            t_b = threading.Thread(target=worker, args=(1,))
            t_b.start()
            deadline = time.monotonic() + 10
            while mb.stats()["queue_depth"] < 1:  # B holds the seat
                assert time.monotonic() < deadline
                time.sleep(0.005)
            with pytest.raises(Overloaded) as err:
                mb.submit({"x": np.full((1, 1), 2.0)})
            assert err.value.retry_after_s == 2.5
            gate.release.set()
            t_a.join(timeout=10)
            t_b.join(timeout=10)
            # The accepted requests completed despite the shed.
            assert not isinstance(results[0], Exception)
            assert not isinstance(results[1], Exception)
            assert mb.stats()["shed"] == 1
        finally:
            gate.release.set()
            mb.close()


class TestCloseFailsQueuedEntries:
    """Satellite regression: close() must resolve EVERY queued entry
    with BatcherClosed — including requests already queued when close
    begins — while dispatched batches complete; no path may hang."""

    def test_queued_entries_raise_dispatched_completes(self):
        gate = _GatedPredict()
        mb = MicroBatcher(gate, max_batch_size=1, batch_timeout_s=0.001,
                          allowed_batch_sizes=[1], in_flight=1,
                          name="ft-close")
        results = {}

        def worker(i):
            try:
                results[i] = mb.submit({"x": np.full((1, 1), float(i))})
            except Exception as exc:  # noqa: BLE001 — the point
                results[i] = exc

        threads = [threading.Thread(target=worker, args=(0,))]
        threads[0].start()
        assert gate.entered.wait(timeout=10)  # 0 is mid-dispatch
        for i in (1, 2):
            t = threading.Thread(target=worker, args=(i,))
            t.start()
            threads.append(t)
        deadline = time.monotonic() + 10
        while mb.stats()["queue_depth"] < 2:
            assert time.monotonic() < deadline
            time.sleep(0.005)

        closer = threading.Thread(target=mb.close)
        closer.start()
        # Queued entries resolve promptly — close() must not hold them
        # hostage to the wedged in-flight batch.
        for i in (1, 2):
            threads[i].join(timeout=10)
            assert not threads[i].is_alive(), f"request {i} hung"
            assert isinstance(results[i], BatcherClosed), results[i]
        gate.release.set()
        threads[0].join(timeout=10)
        closer.join(timeout=10)
        assert not closer.is_alive()
        # The dispatched batch kept its result.
        assert not isinstance(results[0], Exception), results[0]

    def test_bucketed_submit_after_close_raises(self):
        from kubeflow_tpu.serving.model_server import BucketedLMBatcher

        bmb = BucketedLMBatcher(lambda i: i, buckets=[8],
                                name="ft-bucket-closed")
        bmb.close()
        with pytest.raises(BatcherClosed):
            bmb.submit({"tokens": np.ones((1, 4), np.int32)})

    def test_closed_batcher_falls_back_through_model_server(self):
        """The ModelServer contract that makes fail-at-close safe: a
        BatcherClosed from a dying batcher retries the replacement (or
        the direct path) — the accepted request is never dropped."""
        served = []

        def predict(inputs):
            served.append(True)
            return {"y": np.asarray(inputs["x"])}

        srv = ModelServer()
        srv._models["m"] = {1: LoadedModel(
            name="m", version=1, predict=predict, meta={})}
        srv._base_paths["m"] = "unused"
        mb = MicroBatcher(predict, batch_timeout_s=0.001, name="ft-dead")
        mb.close()
        srv._batchers["m"] = mb  # stale closed batcher (swap race)
        try:
            out = srv.predict("m", {"x": np.zeros((1, 2))})
            assert out["y"].shape == (1, 2)
            assert served  # direct path picked it up
        finally:
            srv.stop()


@pytest.fixture(scope="module")
def engine_model(tmp_path_factory):
    """Tiny exported lm_generate model; yields (spec, server) exactly
    like tests/test_lm_serving.py's fixture, so engine fault tests and
    the reference generate() share identical staged params."""
    import jax

    from kubeflow_tpu.models.transformer import Transformer
    from kubeflow_tpu.serving.export import export
    from kubeflow_tpu.serving.loaders import _model_config

    overrides = {
        "vocab_size": VOCAB, "d_model": 32, "n_layers": 2, "n_heads": 4,
        "n_kv_heads": 2, "d_ff": 64, "head_dim": 8, "max_seq_len": 64,
        "dtype": "float32",
    }
    model = Transformer(_model_config(overrides))
    variables = model.init(
        jax.random.key(SEED), np.zeros((1, PROMPT_LEN), np.int32))
    base = tmp_path_factory.mktemp("ft-models") / "lm"
    export(base, 1, variables,
           loader="kubeflow_tpu.serving.loaders:lm_generate",
           config={"model": overrides,
                   "max_new_tokens": NEW_TOKENS, "temperature": 0.0})
    server = ModelServer()
    server.add_model("lm", str(base))
    yield server.get("lm").predict.engine_spec, server
    server.stop()


def _reference_row(spec, prompt, new):
    from kubeflow_tpu.models.generate import generate

    out, _ = generate(spec["cfg"], spec["params"],
                      np.asarray(prompt, np.int32)[None], spec["decode"])
    return np.asarray(out)[0, :len(prompt) + new].tolist()


class TestEngineDeadlines:
    def test_expired_on_arrival(self, engine_model):
        from kubeflow_tpu.serving.engine import DecodeEngine

        spec, _ = engine_model
        engine = DecodeEngine(spec["cfg"], spec["params"],
                              spec["decode"], slots=1, prefill_len=16,
                              name="ft-arrival")
        try:
            with pytest.raises(DeadlineExceeded):
                engine.submit({"tokens": np.arange(1, 5, dtype=np.int32)},
                              deadline=faults.monotonic() - 1.0)
            assert engine.stats()["deadline_expired"] == 1
        finally:
            engine.close()

    def test_midgeneration_expiry_reclaims_slot_no_corruption(
            self, engine_model):
        """Satellite: a deadline-expired mid-generation request frees
        its slot for a new admission and never corrupts a co-resident
        slot's tokens — both survivors token-identical to single-
        request generate()."""
        import threading

        from kubeflow_tpu.serving.engine import DecodeEngine

        spec, _ = engine_model
        rng = np.random.RandomState(SEED)
        prompt_c = rng.randint(1, VOCAB, size=(6,)).tolist()
        prompt_a = rng.randint(1, VOCAB, size=(5,)).tolist()
        prompt_b = rng.randint(1, VOCAB, size=(7,)).tolist()
        with faults.injected("seed=1;engine.step:sleep=0.05"):
            engine = DecodeEngine(spec["cfg"], spec["params"],
                                  spec["decode"], slots=2,
                                  prefill_len=16, name="ft-reclaim")
            outs: dict = {}

            def client(key, prompt, deadline=None):
                try:
                    outs[key] = engine.submit(
                        {"tokens": np.asarray(prompt, np.int32)},
                        deadline=deadline)
                except Exception as exc:  # noqa: BLE001 — the point
                    outs[key] = exc

            try:
                # C: healthy full-budget request in slot 0.
                t_c = threading.Thread(
                    target=client, args=("c", prompt_c))
                t_c.start()
                # A: full budget (12 steps x >=50 ms) but a 150 ms
                # deadline — guaranteed to expire mid-generation.
                t_a = threading.Thread(
                    target=client, args=("a", prompt_a,
                                         faults.monotonic() + 0.15))
                t_a.start()
                t_a.join(timeout=60)
                assert isinstance(outs["a"], DeadlineExceeded), outs["a"]
                # B: admitted into A's reclaimed slot while C decodes.
                client("b", prompt_b)
                t_c.join(timeout=60)
                stats = engine.stats()
                assert stats["deadline_expired"] == 1
                assert stats["in_flight_requests"] == 0
            finally:
                engine.close()
        # Token identity against single-request generate(): neither the
        # survivor nor the reclaimed-slot request saw A's leftovers.
        for key, prompt in (("c", prompt_c), ("b", prompt_b)):
            got = np.asarray(outs[key]["tokens"])[0].tolist()
            assert got == _reference_row(spec, prompt, NEW_TOKENS), (
                f"request {key!r} drifted after mid-generation abort")

    def test_retired_lagged_request_still_honors_deadline(
            self, engine_model):
        """A deterministically-retired request whose lagged emissions
        are still pending (slot freed at dispatch, delivery waiting on
        sync_lag while another slot keeps stepping) must fail at its
        deadline — under wedged steps that lag is unbounded, and the
        client gets its 504, not a late 200."""
        from kubeflow_tpu.serving.engine import DecodeEngine

        spec, _ = engine_model
        with faults.injected("seed=1;engine.step:sleep=0.08"):
            engine = DecodeEngine(spec["cfg"], spec["params"],
                                  spec["decode"], slots=2,
                                  prefill_len=16, sync_lag=8,
                                  name="ft-lag-dl")
            outs: dict = {}

            def client(key, new, deadline=None):
                try:
                    outs[key] = engine.submit(
                        {"tokens": np.arange(1, 5, dtype=np.int32),
                         "max_new_tokens": new}, deadline=deadline)
                except Exception as exc:  # noqa: BLE001 — the point
                    outs[key] = exc

            try:
                # B (12 slow steps) keeps the loop busy so A's lagged
                # emissions stay parked well past A's deadline.
                t_b = threading.Thread(target=client, args=("b", 12))
                t_b.start()
                t_a = threading.Thread(
                    target=client,
                    args=("a", 2, faults.monotonic() + 0.35))
                t_a.start()
                t_a.join(timeout=60)
                assert isinstance(outs["a"], DeadlineExceeded), outs["a"]
                t_b.join(timeout=60)
                assert not isinstance(outs["b"], Exception), outs["b"]
                assert engine.stats()["in_flight_requests"] == 0
            finally:
                engine.close()

    def test_queued_request_expires_while_slots_busy(self, engine_model):
        from kubeflow_tpu.serving.engine import DecodeEngine

        spec, _ = engine_model
        with faults.injected("seed=1;engine.step:sleep=0.04"):
            engine = DecodeEngine(spec["cfg"], spec["params"],
                                  spec["decode"], slots=1,
                                  prefill_len=16, name="ft-queue-exp")
            holder: dict = {}

            def occupant():
                holder["out"] = engine.submit(
                    {"tokens": np.arange(1, 7, dtype=np.int32)})

            t = threading.Thread(target=occupant)
            try:
                t.start()
                deadline = time.monotonic() + 30
                while engine.stats()["in_flight_requests"] < 1:
                    assert time.monotonic() < deadline
                    time.sleep(0.005)
                with pytest.raises(DeadlineExceeded):
                    engine.submit({"tokens": np.arange(1, 4, dtype=np.int32)},
                                  deadline=faults.monotonic() + 0.1)
                t.join(timeout=60)
                assert "out" in holder  # occupant unaffected
            finally:
                t.join(timeout=60)
                engine.close()


class TestEngineOverload:
    def test_admission_queue_cap_sheds(self, engine_model):
        from kubeflow_tpu.serving.engine import DecodeEngine

        spec, _ = engine_model
        with faults.injected("seed=1;engine.step:sleep=0.04"):
            engine = DecodeEngine(spec["cfg"], spec["params"],
                                  spec["decode"], slots=1,
                                  prefill_len=16, max_queue_depth=1,
                                  overload_retry_after_s=3.0,
                                  name="ft-eng-shed")
            results: dict = {}

            def client(i):
                try:
                    results[i] = engine.submit(
                        {"tokens": np.arange(1, 6, dtype=np.int32)})
                except Exception as exc:  # noqa: BLE001 — the point
                    results[i] = exc

            threads = [threading.Thread(target=client, args=(0,))]
            try:
                threads[0].start()
                deadline = time.monotonic() + 30
                while engine.stats()["in_flight_requests"] < 1:
                    assert time.monotonic() < deadline
                    time.sleep(0.005)
                threads.append(threading.Thread(target=client, args=(1,)))
                threads[1].start()
                while engine.stats()["queue_depth"] < 1:
                    assert time.monotonic() < deadline
                    time.sleep(0.005)
                with pytest.raises(Overloaded) as err:
                    engine.submit({"tokens": np.arange(1, 6, dtype=np.int32)})
                assert err.value.retry_after_s == 3.0
                for t in threads:
                    t.join(timeout=60)
                # Accepted work completed despite the shed.
                assert not isinstance(results[0], Exception)
                assert not isinstance(results[1], Exception)
                stats = engine.stats()
                assert stats["shed"] == 1
                assert stats["requests"] == 2
            finally:
                engine.close()

    def test_alloc_block_fault_aborts_cleanly(self, engine_model):
        """The paged-KV allocator's hook site (engine.alloc_block,
        fired when pages are taken from an admission's reservation):
        an injected raise is a device-allocation death — the loop
        aborts, the waiting client gets the error (never a hang), and
        the closed engine refuses new work."""
        from kubeflow_tpu.serving.engine import DecodeEngine
        from kubeflow_tpu.serving.errors import BatcherClosed

        spec, _ = engine_model
        with faults.injected("seed=1;engine.alloc_block:raise") as inj:
            engine = DecodeEngine(spec["cfg"], spec["params"],
                                  spec["decode"], slots=1,
                                  prefill_len=16, name="ft-alloc")
            try:
                with pytest.raises(Exception) as err:
                    engine.submit(
                        {"tokens": np.arange(1, 6, dtype=np.int32)})
                assert "injected fault" in str(err.value)
                assert inj.fired("engine.alloc_block") >= 1
                with pytest.raises(BatcherClosed):
                    engine.submit(
                        {"tokens": np.arange(1, 6, dtype=np.int32)})
            finally:
                engine.close()


class TestServerInflightCap:
    def test_direct_path_bounded_by_max_inflight(self):
        """The un-batched path has no queue to bound it, so the
        ModelServer-level cap must shed there too: one request in
        flight on the direct path, the next sheds with Overloaded."""
        gate = _GatedPredict()
        srv = ModelServer(max_inflight=1, overload_retry_after_s=4.0)
        srv._models["m"] = {1: LoadedModel(
            name="m", version=1, predict=lambda i: gate(i), meta={})}
        srv._base_paths["m"] = "unused"
        holder: dict = {}
        t = threading.Thread(target=lambda: holder.update(
            out=srv.predict("m", {"x": np.zeros((2, 2))})))
        t.start()
        try:
            assert gate.entered.wait(timeout=10)
            with pytest.raises(Overloaded) as err:
                srv.predict("m", {"x": np.zeros((2, 2))})
            assert err.value.retry_after_s == 4.0
            gate.release.set()
            t.join(timeout=10)
            assert "out" in holder  # accepted request unaffected
            # Cap released: the next request is admitted again.
            out = srv.predict("m", {"x": np.zeros((2, 2))})
            assert out["y"].shape == (2, 2)
        finally:
            gate.release.set()
            t.join(timeout=10)
            srv.stop()

    def test_direct_fallthrough_rechecks_deadline(self):
        """A request whose batcher closed under it (drain/swap race)
        must not fall through to an uninterruptible direct-path
        generation once its deadline is spent — 504, not a late 200."""
        ran = []

        class ClosedThenExpired:
            def submit(self, inputs, deadline=None):
                # Simulate the request's budget dying while it was
                # queued here, then the batcher closing (drain).
                faults.active().advance_clock(10)
                raise BatcherClosed("draining")

            def close(self):
                pass

        srv = ModelServer()
        srv._models["m"] = {1: LoadedModel(
            name="m", version=1,
            predict=lambda i: ran.append(True) or {"y": i["x"]},
            meta={})}
        srv._base_paths["m"] = "unused"
        srv._batchers["m"] = ClosedThenExpired()
        try:
            with faults.injected("seed=0"):
                with pytest.raises(DeadlineExceeded):
                    srv.predict("m", {"x": np.zeros((1, 2))},
                                deadline=faults.monotonic() + 1.0)
            assert not ran, "direct path ran a dead request"
        finally:
            srv.stop()


class TestReloadBreaker:
    def _export_lm(self, base, version):
        import jax

        from kubeflow_tpu.models.transformer import Transformer
        from kubeflow_tpu.serving.export import export
        from kubeflow_tpu.serving.loaders import _model_config

        overrides = {
            "vocab_size": 32, "d_model": 8, "n_layers": 1, "n_heads": 2,
            "n_kv_heads": 2, "d_ff": 16, "head_dim": 4,
            "max_seq_len": 16, "dtype": "float32",
        }
        model = Transformer(_model_config(overrides))
        variables = model.init(jax.random.key(0),
                               np.zeros((1, 4), np.int32))
        export(base, version, variables,
               loader="kubeflow_tpu.serving.loaders:lm",
               config=overrides)

    def test_corrupt_version_trips_breaker_last_good_serves(
            self, tmp_path):
        base = tmp_path / "lm"
        self._export_lm(base, 1)
        with faults.injected("seed=0") as inj:
            srv = ModelServer(reload_backoff_s=0.5,
                              reload_backoff_cap_s=8.0)
            srv.add_model("lm", str(base))
            assert srv.get("lm").version == 1
            loads_after_v1 = inj.fired("loader.load")
            # Corrupt version 2 lands in the watch path.
            (base / "2").mkdir()
            (base / "2" / "model.json").write_text("{corrupt")
            with pytest.raises(Exception):
                srv.reload("lm")
            attempts = inj.fired("loader.load")
            assert attempts == loads_after_v1 + 1
            # Breaker OPEN: watcher-style polls skip the loader — no
            # hot-loop on the corrupt artifact.
            for _ in range(8):
                assert srv.reload("lm") is False
            assert inj.fired("loader.load") == attempts
            # Last-good keeps serving.
            out = srv.predict(
                "lm", {"tokens": np.asarray([[1, 2, 3]], np.int32)})
            assert "logits" in out
            assert srv.get("lm").version == 1
            # Backoff elapsed (policy clock) -> HALF-OPEN: one trial.
            inj.advance_clock(60)
            with pytest.raises(Exception):
                srv.reload("lm")
            assert inj.fired("loader.load") == attempts + 1
            # Re-opened with doubled backoff: skipped again.
            assert srv.reload("lm") is False
            assert inj.fired("loader.load") == attempts + 1
            # A NEW good version resets the breaker immediately.
            self._export_lm(base, 3)
            assert srv.reload("lm") is True
            assert srv.get("lm").version == 3
            srv.stop()
        from kubeflow_tpu.runtime.prom import REGISTRY

        rendered = REGISTRY.render()
        line = [ln for ln in rendered.splitlines() if ln.startswith(
            'kft_serving_reload_failures_total{model="lm"}')]
        assert line and float(line[0].rsplit(" ", 1)[1]) >= 2

    def test_half_open_admits_exactly_one_trial(self):
        with faults.injected("seed=0") as inj:
            breaker = _ReloadBreaker(base_s=1.0, cap_s=8.0)
            breaker.record_failure(2)
            assert not breaker.allow(2)  # open
            inj.advance_clock(10)
            assert breaker.allow(2)       # the half-open trial
            assert not breaker.allow(2)   # concurrent poll: refused
            breaker.record_failure(2)     # trial failed -> re-opened
            assert not breaker.allow(2)
            breaker.record_success()
            assert breaker.allow(2)

    def test_new_version_resets_breaker(self):
        breaker = _ReloadBreaker(base_s=100.0)
        breaker.record_failure(2)
        assert not breaker.allow(2)
        assert breaker.allow(3)  # different artifact: try at once


class TestReloadBreakerBackoffBounds:
    """White-box invariants of the breaker's backoff schedule: the
    jittered window must stay inside [B, 1.25*B] for B = min(cap,
    base * 2^(n-1)) — a jitter that can exceed the bound turns the cap
    into a lie, and one that can undershoot re-opens the hot-loop the
    breaker exists to prevent.  Clock-skew driven: no wall sleeps."""

    def test_backoff_window_within_jitter_bounds_per_failure(self):
        import random

        base_s, cap_s = 0.5, 8.0
        with faults.injected("seed=0"):
            breaker = _ReloadBreaker(base_s=base_s, cap_s=cap_s,
                                     rng=random.Random(7))
            for n in range(1, 10):
                before = faults.monotonic()
                breaker.record_failure(2)
                window = breaker.open_until - before
                expected = min(cap_s, base_s * (2 ** (n - 1)))
                # record_failure read the clock a hair after `before`,
                # so `window` can only exceed the nominal bound.
                assert expected <= window <= expected * 1.25 + 1e-6, (
                    n, window, expected)

    def test_jitter_sequences_differ_across_default_breakers(self):
        # OS-seeded default rngs: two replicas watching one model path
        # must not walk identical backoff schedules (lockstep retry).
        with faults.injected("seed=0"):
            windows = []
            for _ in range(2):
                b = _ReloadBreaker(base_s=1.0, cap_s=64.0)
                seq = []
                for _ in range(6):
                    before = faults.monotonic()
                    b.record_failure(2)
                    seq.append(round(b.open_until - before, 9))
                windows.append(seq)
            assert windows[0] != windows[1]

    def test_half_open_single_trial_under_concurrent_clock_skew(self):
        """After the (skewed-past) backoff expires, exactly ONE caller
        may claim the trial slot no matter how many race for it; a
        failed trial re-opens with a doubled window, a successful one
        closes the breaker for everyone."""
        with faults.injected("seed=0") as inj:
            breaker = _ReloadBreaker(base_s=1.0, cap_s=64.0)
            breaker.record_failure(5)
            first_window = breaker.open_until - faults.monotonic()
            inj.advance_clock(2.0)  # backoff spent

            grants = []
            barrier = threading.Barrier(8)

            def racer():
                barrier.wait()
                if breaker.allow(5):
                    grants.append(threading.get_ident())

            threads = [threading.Thread(target=racer)
                       for _ in range(8)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert len(grants) == 1, grants
            # Trial fails -> re-open, doubled (jittered) window; the
            # skewed clock is the only time source consulted.
            before = faults.monotonic()
            breaker.record_failure(5)
            second_window = breaker.open_until - before
            assert second_window >= 2.0 > first_window / 1.25
            assert not breaker.allow(5)
            inj.advance_clock(second_window + 0.001)
            assert breaker.allow(5)      # next half-open trial
            breaker.record_success()
            # Closed: every caller admitted again, immediately.
            assert breaker.allow(5) and breaker.allow(5)


class TestReadinessAndDrain:
    def test_ready_requires_models_and_not_draining(self):
        srv = ModelServer()
        assert not srv.is_ready()  # nothing loaded yet
        srv._models["m"] = {1: LoadedModel(
            name="m", version=1, predict=lambda i: i, meta={})}
        assert srv.is_ready()
        srv.begin_drain()
        assert srv.draining() and not srv.is_ready()

    def test_readyz_flips_healthz_stays(self):
        from kubeflow_tpu.serving.http import make_http_server

        srv = ModelServer()
        srv._models["m"] = {1: LoadedModel(
            name="m", version=1, predict=lambda i: i, meta={})}
        httpd, _ = make_http_server(srv, port=0, host="127.0.0.1")
        port = httpd.server_address[1]
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/readyz", timeout=30) as r:
                assert r.status == 200
                assert json.loads(r.read())["status"] == "ready"
            srv.begin_drain()
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/readyz", timeout=30)
            assert err.value.code == 503
            assert json.loads(err.value.read())["status"] == "draining"
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/healthz", timeout=30) as r:
                assert r.status == 200  # alive while draining
        finally:
            httpd.shutdown()
            srv.stop()

    def test_wait_for_drain_tracks_inflight(self):
        from kubeflow_tpu.serving.main import wait_for_drain

        gate = _GatedPredict()
        srv = ModelServer()
        srv._models["m"] = {1: LoadedModel(
            name="m", version=1,
            predict=lambda i: gate(i), meta={})}
        srv._base_paths["m"] = "unused"
        holder: dict = {}
        t = threading.Thread(target=lambda: holder.update(
            out=srv.predict("m", {"x": np.zeros((2, 2))})))
        t.start()
        try:
            assert gate.entered.wait(timeout=10)
            assert srv.inflight() == 1
            assert not wait_for_drain(srv, deadline_s=0.2)
            gate.release.set()
            t.join(timeout=10)
            assert srv.inflight() == 0
            assert wait_for_drain(srv, deadline_s=5.0)
            assert "out" in holder  # the accepted request completed
        finally:
            gate.release.set()
            t.join(timeout=10)
            srv.stop()


class _Raiser:
    """Stub batcher raising a scripted error from submit()."""

    def __init__(self, exc):
        self.exc = exc

    def submit(self, inputs, deadline=None):
        raise self.exc

    def close(self):
        pass


def _stub_server(exc):
    srv = ModelServer()
    srv._models["m"] = {1: LoadedModel(
        name="m", version=1,
        predict=lambda i: {"y": np.asarray(i["x"])}, meta={})}
    srv._base_paths["m"] = "unused"
    srv._batchers["m"] = _Raiser(exc)
    return srv


class TestHTTPStatusMapping:
    def _post(self, port, body):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/model/m:predict",
            data=json.dumps(body).encode())
        try:
            with urllib.request.urlopen(req, timeout=30) as resp:
                return resp.status, dict(resp.headers), \
                    json.loads(resp.read())
        except urllib.error.HTTPError as e:
            return e.code, dict(e.headers), json.loads(e.read())

    def test_overloaded_maps_to_429_with_retry_after(self):
        from kubeflow_tpu.serving.http import make_http_server

        srv = _stub_server(Overloaded("queue full", retry_after_s=7))
        httpd, _ = make_http_server(srv, port=0, host="127.0.0.1")
        try:
            code, headers, payload = self._post(
                httpd.server_address[1],
                {"instances": [{"x": [1.0]}]})
            assert code == 429
            assert headers.get("Retry-After") == "7"
            assert "queue full" in payload["error"]
        finally:
            httpd.shutdown()
            srv.stop()

    def test_deadline_maps_to_504(self):
        from kubeflow_tpu.serving.http import make_http_server

        srv = _stub_server(DeadlineExceeded("expired mid-generation"))
        httpd, _ = make_http_server(srv, port=0, host="127.0.0.1")
        try:
            code, _, payload = self._post(
                httpd.server_address[1],
                {"instances": [{"x": [1.0]}]})
            assert code == 504
            assert "expired" in payload["error"]
        finally:
            httpd.shutdown()
            srv.stop()

    def test_malformed_deadline_ms_is_400(self):
        from kubeflow_tpu.serving.http import make_http_server

        srv = _stub_server(RuntimeError("unreached"))
        httpd, _ = make_http_server(srv, port=0, host="127.0.0.1")
        try:
            # Non-positive, wrong-typed, and non-finite (NaN would
            # otherwise pass `<= 0` and enforce nothing) all map to
            # the documented 400, never a 500.
            for bad in (0, -5, [500], "soon", float("nan")):
                code, _, payload = self._post(
                    httpd.server_address[1],
                    {"instances": [{"x": [1.0]}],
                     "deadline_ms": bad})
                assert code == 400, (bad, code, payload)
        finally:
            httpd.shutdown()
            srv.stop()


class TestGRPCStatusMapping:
    def test_overloaded_roundtrips_as_typed_error(self):
        from kubeflow_tpu.serving.grpc_server import (
            PredictionClient,
            make_grpc_server,
        )

        srv = _stub_server(Overloaded("engine queue full",
                                      retry_after_s=2))
        server = make_grpc_server(srv, port=0, host="127.0.0.1")
        client = PredictionClient(f"127.0.0.1:{server.bound_port}")
        try:
            with pytest.raises(Overloaded,
                               match="engine queue full") as err:
                client.predict("m", {"x": np.ones((1, 2), np.float32)})
            # The server's Retry-After hint survives the wire — clients
            # backing off via the typed field honor the server's number.
            assert err.value.retry_after_s == 2.0
        finally:
            client.close()
            server.stop(0)
            srv.stop()

    def test_server_deadline_roundtrips_as_typed_error(self):
        from kubeflow_tpu.serving.grpc_server import (
            PredictionClient,
            make_grpc_server,
        )

        srv = _stub_server(DeadlineExceeded("expired in queue"))
        server = make_grpc_server(srv, port=0, host="127.0.0.1")
        client = PredictionClient(f"127.0.0.1:{server.bound_port}")
        try:
            with pytest.raises(DeadlineExceeded):
                client.predict("m", {"x": np.ones((1, 2), np.float32)})
        finally:
            client.close()
            server.stop(0)
            srv.stop()

    def test_transport_timeout_maps_to_deadline_exceeded(self):
        """Satellite: a client-supplied deadline that the transport
        itself enforces (server too slow to answer at all) surfaces as
        the SAME typed error as a server-side expiry."""
        from kubeflow_tpu.serving.grpc_server import (
            PredictionClient,
            make_grpc_server,
        )

        gate = _GatedPredict()
        srv = ModelServer()
        srv._models["m"] = {1: LoadedModel(
            name="m", version=1, predict=lambda i: gate(i), meta={})}
        srv._base_paths["m"] = "unused"
        server = make_grpc_server(srv, port=0, host="127.0.0.1")
        client = PredictionClient(f"127.0.0.1:{server.bound_port}")
        try:
            with pytest.raises(DeadlineExceeded):
                client.predict("m", {"x": np.ones((2, 2), np.float32)},
                               timeout=0.2)
        finally:
            gate.release.set()
            client.close()
            server.stop(0)
            srv.stop()

    def test_client_timeouts_default_to_none(self):
        """Satellite: no more hard-coded 60 s — the client sends no
        deadline unless the caller supplies one."""
        import inspect

        from kubeflow_tpu.serving.grpc_server import PredictionClient

        for method in ("predict", "classify", "metadata"):
            sig = inspect.signature(getattr(PredictionClient, method))
            assert sig.parameters["timeout"].default is None, method


class TestEngineDrainDeadlineSkew:
    def test_drain_deadline_expires_under_skewed_policy_clock(
            self, engine_model):
        """PR-8 satellite: the engine's close() drain deadline rides
        the POLICY clock (faults.monotonic), so a seeded skew expires
        it without waiting out the drain budget.  Each step adds 500 s
        of skew: the step AFTER close() arms the deadline pushes the
        clock past it, the loop aborts the in-flight request, and
        close() returns in wall-milliseconds despite drain_s=60.  On
        the real clock (the pre-migration bug) the request would
        simply complete inside the budget and no abort would fire."""
        from kubeflow_tpu.serving.engine import DecodeEngine

        spec, _ = engine_model
        rng = np.random.RandomState(SEED)
        prompt = rng.randint(1, VOCAB, size=(6,)).tolist()
        with faults.injected(
                "seed=1;engine.step:sleep=0.05;engine.step:skew=500"):
            engine = DecodeEngine(spec["cfg"], spec["params"],
                                  spec["decode"], slots=1,
                                  prefill_len=16, name="ft-drain-skew")
            outs: dict = {}

            def client():
                try:
                    outs["r"] = engine.submit(
                        {"tokens": np.asarray(prompt, np.int32)})
                except Exception as exc:  # noqa: BLE001 — the point
                    outs["r"] = exc
            t = threading.Thread(target=client)
            t.start()
            deadline = time.monotonic() + 30
            while not engine.stats()["in_flight_requests"]:
                assert time.monotonic() < deadline, "never admitted"
                time.sleep(0.01)
            t0 = time.monotonic()
            engine.close(drain_s=60.0)
            wall = time.monotonic() - t0
            t.join(timeout=30)
            assert isinstance(outs.get("r"), RuntimeError), outs
            assert "drain deadline" in str(outs["r"])
            # Skew, not wall time, expired the drain: 60 s of budget
            # consumed in well under 30 s of real time.
            assert wall < 30.0, wall
