"""Weight-only int8 serving quantization: roundtrip error, decode path,
loader integration.  New TPU-first capability — the reference served
float SavedModels only (kubeflow/tf-serving/tf-serving.libsonnet)."""

import jax
import jax.numpy as jnp
import numpy as np

from kubeflow_tpu.models.generate import DecodeConfig, generate
from kubeflow_tpu.models.transformer import Transformer, TransformerConfig
from kubeflow_tpu.ops.quantize import (
    QTensor,
    embed_lookup,
    qeinsum,
    quantize_params,
)

CFG = TransformerConfig(
    vocab_size=128, d_model=32, n_layers=2, n_heads=4, n_kv_heads=4,
    d_ff=64, head_dim=8, max_seq_len=64, dtype=jnp.float32,
)


def _params(seed=0):
    from flax import linen as nn

    model = Transformer(CFG)
    toks = jnp.zeros((1, 8), jnp.int32)
    # Unboxed, like orbax-restored serving checkpoints; quantize_params
    # also works through flax partitioning boxes (loader test covers it).
    return nn.unbox(model.init(jax.random.key(seed), toks)["params"])


class TestQuantizeParams:
    def test_known_weights_become_qtensors(self):
        q = quantize_params(_params())
        layers = q["layers"]
        assert isinstance(layers["attn"]["wq"], QTensor)
        assert isinstance(layers["mlp"]["wi"], QTensor)
        assert isinstance(q["embed"], QTensor)
        # Norm scales stay full precision.
        assert not isinstance(layers["attn_norm"]["scale"], QTensor)
        assert layers["attn"]["wq"].values.dtype == jnp.int8

    def test_per_channel_roundtrip_error_bounded(self):
        p = _params()
        q = quantize_params(p)
        for name in ("wq", "wo"):
            orig = np.asarray(p["layers"]["attn"][name], np.float32)
            deq = np.asarray(
                q["layers"]["attn"][name].astype(jnp.float32))
            # Symmetric int8: error <= scale/2 = amax/254 per channel.
            err = np.abs(orig - deq)
            assert err.max() <= np.abs(orig).max() / 254 + 1e-7

    def test_qeinsum_matches_dequantized_dense(self):
        p = _params()
        q = quantize_params(p)
        x = jnp.asarray(
            np.random.RandomState(0).randn(2, 3, CFG.d_model), jnp.float32)
        wq = q["layers"]["attn"]["wq"][0]       # one layer [e, h, d]
        got = qeinsum("bse,ehd->bshd", x, wq, jnp.float32)
        want = jnp.einsum(
            "bse,ehd->bshd", x, wq.astype(jnp.float32))
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)

    def test_embed_lookup_matches_dequant_gather(self):
        q = quantize_params(_params())
        toks = jnp.asarray([[1, 5, 7]], jnp.int32)
        got = embed_lookup(q["embed"], toks, jnp.float32)
        want = q["embed"].astype(jnp.float32)[toks]
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-6)


class TestQuantizedDecode:
    def test_generate_runs_and_tracks_fp32(self):
        p = _params()
        q = quantize_params(p)
        prompt = jnp.asarray(
            np.random.RandomState(1).randint(1, CFG.vocab_size, (2, 8)),
            jnp.int32)
        dec = DecodeConfig(max_new_tokens=8)
        toks_f, logits_f = generate(CFG, p, prompt, dec)
        toks_q, logits_q = generate(CFG, q, prompt, dec)
        assert toks_q.shape == toks_f.shape == (2, 16)
        assert np.isfinite(np.asarray(logits_q)).all()
        # Per-channel int8 keeps final logits close on a tiny model; the
        # decode trajectory may legitimately diverge after sampling, so
        # compare one prefill-step's logits instead of token ids.
        _, l_f = generate(CFG, p, prompt, DecodeConfig(max_new_tokens=1))
        _, l_q = generate(CFG, q, prompt, DecodeConfig(max_new_tokens=1))
        cos = np.sum(np.asarray(l_f) * np.asarray(l_q)) / (
            np.linalg.norm(l_f) * np.linalg.norm(l_q) + 1e-9)
        assert cos > 0.99, cos


class TestLoaderIntegration:
    def test_lm_generate_quantize_config(self, tmp_path):
        from kubeflow_tpu.serving.export import export
        from kubeflow_tpu.serving.model_server import ModelServer

        model = Transformer(CFG)
        variables = model.init(jax.random.key(0),
                               jnp.zeros((1, 8), jnp.int32))
        overrides = {
            "vocab_size": CFG.vocab_size, "d_model": CFG.d_model,
            "n_layers": CFG.n_layers, "n_heads": CFG.n_heads,
            "n_kv_heads": CFG.n_kv_heads, "d_ff": CFG.d_ff,
            "head_dim": CFG.head_dim, "max_seq_len": CFG.max_seq_len,
            "dtype": "float32",
        }
        export(str(tmp_path / "lm"), 1, variables,
               loader="kubeflow_tpu.serving.loaders:lm_generate",
               config={"model": overrides, "max_new_tokens": 4,
                       "temperature": 0.0, "quantize": "int8"})
        server = ModelServer()
        server.add_model("lm", str(tmp_path / "lm"))
        out = server.predict(
            "lm", {"tokens": np.asarray([[3, 1, 4]], np.int32)})
        assert np.asarray(out["tokens"]).shape == (1, 7)

    def test_unknown_quantize_mode_rejected(self):
        import pytest

        from kubeflow_tpu.serving.loaders import lm_generate

        with pytest.raises(ValueError, match="quantize"):
            lm_generate({"quantize": "fp4"})


class TestNarrowParams:
    def test_matmul_weights_narrow_norms_stay_f32(self):
        from kubeflow_tpu.ops.quantize import narrow_params

        p = _params()
        n = narrow_params(p, jnp.bfloat16)
        assert n["layers"]["attn"]["wq"].dtype == jnp.bfloat16
        assert n["embed"].dtype == jnp.bfloat16
        # nn.scan-stacked per-layer norm scales are 2-D [L, d] — a rank
        # heuristic would narrow them; the contraction table must not.
        assert n["layers"]["attn_norm"]["scale"].dtype == jnp.float32
        assert n["layers"]["attn_norm"]["scale"].ndim == 2
        assert n["final_norm"]["scale"].dtype == jnp.float32


class TestInt8KVCache:
    def test_quantized_cache_decode_tracks_native(self):
        p = _params()
        prompt = jnp.asarray(
            np.random.RandomState(3).randint(1, CFG.vocab_size, (2, 8)),
            jnp.int32)
        _, l_native = generate(
            CFG, p, prompt, DecodeConfig(max_new_tokens=4))
        toks, l_q8 = generate(
            CFG, p, prompt,
            DecodeConfig(max_new_tokens=4, kv_cache_dtype="int8"))
        assert toks.shape == (2, 12)
        assert np.isfinite(np.asarray(l_q8)).all()
        cos = np.sum(np.asarray(l_native) * np.asarray(l_q8)) / (
            np.linalg.norm(l_native) * np.linalg.norm(l_q8) + 1e-9)
        assert cos > 0.99, cos

    def test_loader_kv_cache_config(self, tmp_path):
        from kubeflow_tpu.serving.export import export
        from kubeflow_tpu.serving.model_server import ModelServer

        model = Transformer(CFG)
        variables = model.init(jax.random.key(0),
                               jnp.zeros((1, 8), jnp.int32))
        overrides = {
            "vocab_size": CFG.vocab_size, "d_model": CFG.d_model,
            "n_layers": CFG.n_layers, "n_heads": CFG.n_heads,
            "n_kv_heads": CFG.n_kv_heads, "d_ff": CFG.d_ff,
            "head_dim": CFG.head_dim, "max_seq_len": CFG.max_seq_len,
            "dtype": "float32",
        }
        export(str(tmp_path / "lm"), 1, variables,
               loader="kubeflow_tpu.serving.loaders:lm_generate",
               config={"model": overrides, "max_new_tokens": 4,
                       "quantize": "int8", "kv_cache": "int8"})
        server = ModelServer()
        server.add_model("lm", str(tmp_path / "lm"))
        out = server.predict(
            "lm", {"tokens": np.asarray([[3, 1, 4]], np.int32)})
        assert np.asarray(out["tokens"]).shape == (1, 7)

    def test_unknown_kv_cache_mode_rejected(self):
        import pytest

        from kubeflow_tpu.serving.loaders import lm_generate

        with pytest.raises(ValueError, match="kv_cache"):
            lm_generate({"kv_cache": "fp8"})
