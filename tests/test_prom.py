"""Prometheus exposition: the metrics endpoints the reference never had
(SURVEY.md §5 "No Prometheus, no metrics endpoints")."""

import urllib.request

import numpy as np

from kubeflow_tpu.runtime.prom import (
    Registry,
    parse_metrics,
    sample_value,
    serve_metrics,
)


class TestRegistry:
    def test_counter_gauge_render(self):
        reg = Registry()
        reg.counter("reqs_total", "requests").inc(model="m1")
        reg.counter("reqs_total").inc(2.0, model="m1")
        reg.gauge("jobs", "by phase").set(3, phase="Running")
        text = reg.render()
        assert "# TYPE reqs_total counter" in text
        assert 'reqs_total{model="m1"} 3.0' in text
        assert "# HELP jobs by phase" in text
        assert 'jobs{phase="Running"} 3.0' in text

    def test_histogram_buckets_cumulative(self):
        reg = Registry()
        h = reg.histogram("lat_seconds", "latency", buckets=(0.1, 1.0))
        for v in (0.05, 0.5, 5.0):
            h.observe(v)
        text = reg.render()
        assert 'lat_seconds_bucket{le="0.1"} 1' in text
        assert 'lat_seconds_bucket{le="1.0"} 2' in text
        assert 'lat_seconds_bucket{le="+Inf"} 3' in text
        assert "lat_seconds_count 3" in text
        np.testing.assert_allclose(
            float(text.split("lat_seconds_sum ")[1].split("\n")[0]), 5.55)

    def test_kind_conflict_rejected(self):
        import pytest

        reg = Registry()
        reg.counter("x")
        with pytest.raises(ValueError, match="registered"):
            reg.gauge("x")

    def test_bucket_conflict_rejected(self):
        # Silent first-registration-wins would hand the second caller a
        # histogram with someone else's buckets.
        import pytest

        reg = Registry()
        reg.histogram("h", buckets=(1.0, 2.0))
        reg.histogram("h", buckets=(2.0, 1.0))  # same set: fine
        reg.histogram("h")  # default-bucket request reuses existing
        with pytest.raises(ValueError, match="buckets"):
            reg.histogram("h", buckets=(1.0, 5.0))

    def test_labeled_zero_state_via_declare(self):
        reg = Registry()
        h = reg.histogram("h", buckets=(1.0,)).declare(shard="a")
        assert 'h_count{shard="a"} 0' in reg.render()
        h.observe(0.5, shard="b")
        text = reg.render()
        # Declared-idle series survives another label observing.
        assert 'h_count{shard="a"} 0' in text
        assert 'h_count{shard="b"} 1' in text

    def test_histogram_scrape_never_tears_sum_against_count(self):
        # Torn-read audit: render() must snapshot a series' bucket
        # counts AND its sum under the metric lock in one motion.  A
        # concurrent observe() landing between the two reads would
        # scrape a _count that disagrees with _sum — here every
        # observation is exactly 1.0, so any honest scrape satisfies
        # sum == count (and cumulative bucket monotonicity) no matter
        # when it lands.
        import threading

        reg = Registry()
        h = reg.histogram("t_seconds", "torn-read probe",
                          buckets=(0.5, 2.0))
        stop = threading.Event()

        def writer():
            while not stop.is_set():
                h.observe(1.0, shard="w")

        threads = [threading.Thread(target=writer) for _ in range(4)]
        for t in threads:
            t.start()
        try:
            for _ in range(200):
                parsed = parse_metrics(reg.render())
                count = sample_value(parsed, "t_seconds_count",
                                     shard="w")
                if count is None:
                    continue  # nothing observed yet
                total = sample_value(parsed, "t_seconds_sum",
                                     shard="w")
                assert total == count, (
                    f"torn scrape: sum {total} != count {count} with "
                    f"all-1.0 observations")
                le_half = sample_value(parsed, "t_seconds_bucket",
                                       shard="w", le="0.5")
                le_two = sample_value(parsed, "t_seconds_bucket",
                                      shard="w", le="2.0")
                le_inf = sample_value(parsed, "t_seconds_bucket",
                                      shard="w", le="+Inf")
                assert le_half == 0
                assert le_two == le_inf == count, (
                    f"non-cumulative buckets: {le_two}/{le_inf} vs "
                    f"count {count}")
        finally:
            stop.set()
            for t in threads:
                t.join()


class TestParseMetrics:
    """parse_metrics is render's inverse for the three line shapes this
    module emits — the fleet registry/autoscaler scrape path."""

    def test_roundtrip_counter_gauge_histogram(self):
        reg = Registry()
        reg.counter("c_total", "c").inc(3, model="m")
        reg.gauge("g", "g").set(7)
        reg.gauge("g").set(2, model="m")
        reg.histogram("h_seconds", "h").observe(0.2)
        parsed = parse_metrics(reg.render())
        assert sample_value(parsed, "c_total", model="m") == 3.0
        assert sample_value(parsed, "g") == 7.0  # unlabeled first
        assert sample_value(parsed, "g", model="m") == 2.0
        assert sample_value(parsed, "h_seconds_count") == 1.0
        assert sample_value(parsed, "missing") is None

    def test_exact_label_match_beats_first_superset(self):
        # Regression (§5.11 satellite): sample_value returned the FIRST
        # sample whose labels were a superset of the request, so asking
        # for metric(model="lm") when an adapter-refined series
        # {model="lm", adapter="a"} rendered first answered the
        # refinement, not the aggregate.  An exact label-set match must
        # win whenever one exists; the superset fallback stays for
        # callers that underspecify on purpose.
        reg = Registry()
        ctr = reg.counter("reqs_total", "r")
        ctr.inc(5, model="lm", adapter="a")   # renders before the
        ctr.inc(2, model="lm")                # label-sparser series
        parsed = parse_metrics(reg.render())
        assert sample_value(parsed, "reqs_total", model="lm") == 2.0
        assert sample_value(parsed, "reqs_total",
                            model="lm", adapter="a") == 5.0
        # No exact match -> first superset still answers (the
        # underspecified read callers rely on).
        only_refined = parse_metrics(
            'reqs_total{adapter="a",model="lm"} 5\n'
            'reqs_total{adapter="b",model="lm"} 7\n')
        assert sample_value(only_refined, "reqs_total",
                            model="lm") == 5.0

    def test_garbage_lines_skipped_not_fatal(self):
        parsed = parse_metrics(
            "# HELP x y\nnot a metric line !!\nx 1.5\nx{a=\"b\"} nan?\n")
        assert parsed == {"x": [({}, 1.5)]}

    def test_escaped_label_values_roundtrip(self):
        reg = Registry()
        reg.gauge("g", "").set(1, path='a"b\\c')
        parsed = parse_metrics(reg.render())
        labels, value = parsed["g"][0]
        assert value == 1.0 and labels["path"] == 'a"b\\c'

    def test_backslash_adjacent_escapes_roundtrip(self):
        # Regression: sequential replace-based unescaping turned the
        # rendered form of backslash+'n' (r'\\n') into
        # backslash+newline.  Single-pass unescape must invert render
        # exactly for every escape-adjacent pairing.
        for value in ("C:\\new", "tab\\\\n", 'q\\"x', "a\nb\\"):
            reg = Registry()
            reg.gauge("g", "").set(1, path=value)
            parsed = parse_metrics(reg.render())
            labels, _ = parsed["g"][0]
            assert labels["path"] == value, (value, labels)


class TestServingLoadGauges:
    """Satellite: in-flight/queue/readiness visible on /metrics (not
    just the per-model :stats JSON), refreshed at scrape time."""

    def test_refresh_gauges_exports_inflight_and_readiness(self):
        from kubeflow_tpu.runtime import prom
        from kubeflow_tpu.serving.model_server import (
            LoadedModel,
            ModelServer,
        )

        srv = ModelServer()
        srv._models["m"] = {1: LoadedModel(
            name="m", version=1, predict=lambda i: i, meta={})}
        srv._inflight_by_model["m"] = 2
        srv.enter_request()
        srv.enter_request()
        try:
            srv.refresh_gauges()
            parsed = parse_metrics(prom.REGISTRY.render())
            assert sample_value(parsed, "kft_serving_inflight") == 2.0
            assert sample_value(parsed, "kft_serving_inflight",
                                model="m") == 2.0
            assert sample_value(parsed, "kft_serving_queue_depth",
                                model="m") == 0.0
            assert sample_value(parsed, "kft_serving_ready") == 1.0
            srv.begin_drain()
            srv.refresh_gauges()
            parsed = parse_metrics(prom.REGISTRY.render())
            assert sample_value(parsed, "kft_serving_ready") == 0.0
        finally:
            srv.exit_request()
            srv.exit_request()

    def test_metrics_route_refreshes_before_render(self):
        import json

        from kubeflow_tpu.runtime import prom
        from kubeflow_tpu.serving.http import make_http_server
        from kubeflow_tpu.serving.model_server import (
            LoadedModel,
            ModelServer,
        )

        srv = ModelServer()
        srv._models["m"] = {1: LoadedModel(
            name="m", version=1, predict=lambda i: i, meta={})}
        httpd, _ = make_http_server(srv, port=0, host="127.0.0.1")
        try:
            port = httpd.server_address[1]
            srv.enter_request()  # a real request mid-parse
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics",
                    timeout=30) as resp:
                parsed = parse_metrics(resp.read().decode())
            srv.exit_request()
            # The scrape saw the live in-flight request — proof the
            # refresh ran at render time — and the scrape ITSELF is
            # not counted (probe routes skip the in-flight bracket, or
            # every scrape would feed the autoscaler phantom load).
            assert sample_value(parsed,
                                "kft_serving_inflight") == 1.0
            assert sample_value(parsed, "kft_serving_ready") == 1.0
        finally:
            httpd.shutdown()


class TestServeMetrics:
    def test_http_endpoint(self):
        reg = Registry()
        reg.counter("ticks_total").inc()
        httpd, _ = serve_metrics(0, reg, host="127.0.0.1")
        port = httpd.server_address[1]
        try:
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=5
            ).read().decode()
            assert "ticks_total 1.0" in body
        finally:
            httpd.shutdown()


class TestOperatorMetrics:
    def test_fake_kube_run_exports_job_gauges(self):
        from kubeflow_tpu.operator.gang import GangScheduler
        from kubeflow_tpu.operator.kube import FakeKube
        from kubeflow_tpu.operator.reconciler import TPUJobController
        from kubeflow_tpu.runtime.prom import REGISTRY

        kube = FakeKube()
        kube.create_custom({
            "apiVersion": "kubeflow-tpu.org/v1", "kind": "TPUJob",
            "metadata": {"name": "m", "namespace": "default"},
            "spec": {"sliceType": "v5e-1", "numWorkers": 1,
                     "worker": {"image": "img", "command": ["true"]}},
        })
        TPUJobController(kube, GangScheduler({"v5e-1": 1})).reconcile_all()
        text = REGISTRY.render()
        assert "kft_operator_reconcile_passes_total" in text
        assert 'kft_operator_jobs{phase="Running"}' in text \
            or 'kft_operator_jobs{phase="Starting"}' in text, text


class TestLabelEscaping:
    def test_quote_backslash_newline_escaped(self):
        reg = Registry()
        reg.counter("c").inc(model='a"b\\c\nd')
        text = reg.render()
        assert r'model="a\"b\\c\nd"' in text

    def test_gang_latency_histogram_recorded(self):
        from kubeflow_tpu.runtime.prom import REGISTRY

        # The FakeKube gang from the gauge test above reaches Running
        # via the same controller; a second reconcile records latency
        # once pods run.  Drive a fresh job to Running explicitly.
        from kubeflow_tpu.operator.gang import GangScheduler
        from kubeflow_tpu.operator.kube import RUNNING, FakeKube
        from kubeflow_tpu.operator.reconciler import TPUJobController

        kube = FakeKube()
        kube.create_custom({
            "apiVersion": "kubeflow-tpu.org/v1", "kind": "TPUJob",
            "metadata": {"name": "lat", "namespace": "default"},
            "spec": {"sliceType": "v5e-1", "numWorkers": 1,
                     "worker": {"image": "img", "command": ["true"]}},
        })
        ctl = TPUJobController(kube, GangScheduler({"v5e-1": 1}))
        ctl.reconcile_all()                    # admit + create pods
        for pod in kube.pods.values():         # fake kubelet: run them
            pod["status"]["phase"] = RUNNING
        ctl.reconcile_all()                    # observe gang_running
        text = REGISTRY.render()
        assert "kft_gang_schedule_to_running_seconds_count" in text, text


class TestBatcherMetrics:
    def test_dispatch_records_batch_size_histogram(self):
        from kubeflow_tpu.runtime.prom import REGISTRY
        from kubeflow_tpu.serving.model_server import MicroBatcher

        mb = MicroBatcher(lambda inputs: {"y": inputs["x"]},
                          max_batch_size=2, batch_timeout_s=0.01)
        mb.submit({"x": np.zeros((1, 2))})
        mb.close()
        text = REGISTRY.render()
        assert "kft_serving_batch_size_count" in text

    def test_series_exists_before_first_dispatch(self, monkeypatch):
        # Fresh registry (the global one is shared across tests and the
        # earlier dispatch test already populated it): construction
        # alone must register a scrapeable ZERO-count series — 'no
        # data' on a stuck batcher is indistinguishable from a broken
        # scrape.  The zero series carries the batcher label (declare()
        # at construction), so it survives other batchers observing —
        # the unlabeled fallback used to vanish the moment ANY labeled
        # series appeared.
        import kubeflow_tpu.runtime.prom as prom
        from kubeflow_tpu.serving.model_server import MicroBatcher

        fresh = Registry()
        monkeypatch.setattr(prom, "REGISTRY", fresh)
        mb = MicroBatcher(lambda inputs: inputs, batch_timeout_s=0.01,
                          name="idle")
        try:
            text = fresh.render()
            assert 'kft_serving_batch_size_count{batcher="idle"} 0' \
                in text, text
            # A second batcher observing must not erase the idle one's
            # zero series.
            busy = MicroBatcher(lambda inputs: inputs,
                                batch_timeout_s=0.01, name="busy")
            busy.submit({"x": np.zeros((1, 2))})
            busy.close()
            text = fresh.render()
            assert 'kft_serving_batch_size_count{batcher="idle"} 0' \
                in text, text
            assert 'kft_serving_batch_size_count{batcher="busy"} 1' \
                in text, text
        finally:
            mb.close()
