"""Real two-process rendezvous through runtime/bootstrap.py.

Heir of the reference's `simple_tfjob` E2E — the only test there that
actually ran a multi-pod job through the TF_CONFIG contract
(/root/reference/testing/workflows/components/workflows.libsonnet:398-411).
Here two REAL OS processes run the worker bootstrap (env parse, DNS wait,
``jax.distributed.initialize`` against a localhost coordinator), then
execute one cross-process collective — the seam every previous round
covered only up to, never through.
"""

import os
import socket
import subprocess
import sys

from kubeflow_tpu.runtime import bootstrap

_WORKER = r"""
import os, sys
import jax

jax.config.update("jax_platforms", "cpu")

from kubeflow_tpu.runtime import bootstrap

env = bootstrap.worker_env()
env = bootstrap.initialize(env, wait_coordinator_timeout_s=60.0)

assert jax.process_count() == 2, jax.process_count()
assert jax.process_index() == env.process_id

import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

devs = jax.devices()
assert len(devs) == 2 * jax.local_device_count(), devs
mesh = Mesh(np.array(devs), ("data",))
# Each process contributes its own shard; the jitted sum is a real
# cross-process collective over the distributed backend.
local = np.array([float(env.process_id + 1)], dtype=np.float32)
arr = jax.make_array_from_process_local_data(
    NamedSharding(mesh, P("data")), local)
total = jax.jit(jax.numpy.sum,
                out_shardings=NamedSharding(mesh, P()))(arr)
print(f"RENDEZVOUS process={env.process_id} sum={float(total)}", flush=True)
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_rendezvous_and_psum():
    port = _free_port()
    env_base = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        # One CPU device per process: the 2-process world then has 2
        # global devices and the sum is genuinely cross-process.
        "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
        bootstrap.ENV_COORDINATOR: f"127.0.0.1:{port}",
        bootstrap.ENV_NUM_PROCESSES: "2",
        bootstrap.ENV_JOB_NAME: "rendezvous-test",
    }
    procs = []
    for pid in (0, 1):
        env = {**env_base, bootstrap.ENV_PROCESS_ID: str(pid)}
        procs.append(subprocess.Popen(
            [sys.executable, "-c", _WORKER],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, cwd=os.path.dirname(os.path.dirname(__file__)),
        ))
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=150)
            outs.append((p.returncode, out, err))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for rc, out, err in outs:
        assert rc == 0, f"worker failed rc={rc}\nstdout:{out}\nstderr:{err}"
    # 1.0 + 2.0 over the two processes.
    assert "RENDEZVOUS process=0 sum=3.0" in outs[0][1], outs[0]
    assert "RENDEZVOUS process=1 sum=3.0" in outs[1][1], outs[1]
