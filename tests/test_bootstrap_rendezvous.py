"""Real two-process rendezvous through runtime/bootstrap.py.

Heir of the reference's `simple_tfjob` E2E — the only test there that
actually ran a multi-pod job through the TF_CONFIG contract
(/root/reference/testing/workflows/components/workflows.libsonnet:398-411).
Here two REAL OS processes run the worker bootstrap (env parse, DNS wait,
``jax.distributed.initialize`` against a localhost coordinator), then
execute one cross-process collective — the seam every previous round
covered only up to, never through.
"""

import os
import socket
import subprocess
import sys

from kubeflow_tpu.runtime import bootstrap

_WORKER = r"""
import os, sys
import jax

jax.config.update("jax_platforms", "cpu")

from kubeflow_tpu.runtime import bootstrap

env = bootstrap.worker_env()
env = bootstrap.initialize(env, wait_coordinator_timeout_s=60.0)

assert jax.process_count() == 2, jax.process_count()
assert jax.process_index() == env.process_id

import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

devs = jax.devices()
assert len(devs) == 2 * jax.local_device_count(), devs
mesh = Mesh(np.array(devs), ("data",))
# Each process contributes its own shard; the jitted sum is a real
# cross-process collective over the distributed backend.
local = np.array([float(env.process_id + 1)], dtype=np.float32)
arr = jax.make_array_from_process_local_data(
    NamedSharding(mesh, P("data")), local)
total = jax.jit(jax.numpy.sum,
                out_shardings=NamedSharding(mesh, P()))(arr)
print(f"RENDEZVOUS process={env.process_id} sum={float(total)}", flush=True)
"""


_TRAIN_WORKER = r"""
import os, sys
import jax

jax.config.update("jax_platforms", "cpu")

from kubeflow_tpu.runtime import bootstrap

env = bootstrap.initialize(bootstrap.worker_env(),
                           wait_coordinator_timeout_s=60.0)
assert jax.process_count() == 2

import numpy as np
import optax

from kubeflow_tpu.models.transformer import TransformerConfig, lm_task
from kubeflow_tpu.parallel import MeshSpec
from kubeflow_tpu.runtime.metrics import MetricsLogger
from kubeflow_tpu.runtime.train import Trainer

cfg = TransformerConfig(
    vocab_size=64, d_model=32, n_layers=2, n_heads=4, n_kv_heads=4,
    d_ff=64, head_dim=8, max_seq_len=16, dtype=jax.numpy.float32,
)
mesh = MeshSpec(data=2).build()  # one device per process -> data=2
init_fn, loss_fn = lm_task(cfg, mesh=mesh)
trainer = Trainer(
    init_fn=init_fn, loss_fn=loss_fn, tx=optax.adam(1e-2), mesh=mesh,
    metrics=MetricsLogger(stream=open(os.devnull, "w")),
)

# Each process feeds ONLY its local rows (global batch 4 = 2 x 2);
# Trainer.shard_batch assembles the global array from process-local
# data — no host ever holds the full batch.
rng = np.random.RandomState(env.process_id)


def data():
    while True:
        yield {"tokens": rng.randint(0, 64, size=(2, 16)).astype(np.int32)}


state = trainer.fit(data(), num_steps=3, examples_per_step=4, log_every=0)
# The loss/params are replicated state: both processes must agree
# bit-for-bit (same compiled SPMD program, collectives included).
print(f"TRAIN process={env.process_id} "
      f"loss={trainer.last_metrics['loss']:.6f} "
      f"step={int(state.step)}", flush=True)
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]

def _run_two_workers(worker_src: str, job_name: str, timeout_s: float,
                     devices_per_process: int = 1):
    """Spawn two worker processes against one localhost coordinator and
    return [(rc, stdout, stderr)], asserting both exited cleanly."""
    port = _free_port()
    env_base = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        # devices_per_process=1: the 2-process world has 2 global
        # devices and every collective is cross-process.  >1 models a
        # multi-host slice — an intra-process axis (ICI-like) crossed
        # with the process-spanning axis (DCN-like).
        "XLA_FLAGS": "--xla_force_host_platform_device_count="
                     f"{devices_per_process}",
        bootstrap.ENV_COORDINATOR: f"127.0.0.1:{port}",
        bootstrap.ENV_NUM_PROCESSES: "2",
        bootstrap.ENV_JOB_NAME: job_name,
    }
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", worker_src],
            env={**env_base, bootstrap.ENV_PROCESS_ID: str(pid)},
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            cwd=os.path.dirname(os.path.dirname(__file__)),
        )
        for pid in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=timeout_s)
            outs.append((p.returncode, out, err))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for rc, out, err in outs:
        assert rc == 0, f"worker failed rc={rc}\nstdout:{out}\nstderr:{err}"
    return outs


_SHARDED_TRAIN_WORKER = r"""
import os, sys
import jax

jax.config.update("jax_platforms", "cpu")

from kubeflow_tpu.runtime import bootstrap

env = bootstrap.initialize(bootstrap.worker_env(),
                           wait_coordinator_timeout_s=60.0)
assert jax.process_count() == 2
assert jax.local_device_count() == 2
assert jax.device_count() == 4

import numpy as np
import optax

from kubeflow_tpu.models.transformer import TransformerConfig, lm_task
from kubeflow_tpu.parallel import MeshSpec
from kubeflow_tpu.runtime.metrics import MetricsLogger
from kubeflow_tpu.runtime.train import Trainer

cfg = TransformerConfig(
    vocab_size=64, d_model=32, n_layers=2, n_heads=4, n_kv_heads=4,
    d_ff=64, head_dim=8, max_seq_len=16, dtype=jax.numpy.float32,
)
# data=2 x fsdp=2 over 4 devices, 2 per process: jax.devices() is
# process-major, so the DATA axis spans the process boundary (the DCN
# hop of a multi-host slice) while FSDP weight sharding stays
# intra-process (the ICI hop) — the actual topology of a multi-host
# TPU job, and the configuration the suite previously never modeled.
mesh = MeshSpec(data=2, fsdp=2).build()
for row in mesh.devices.reshape(2, 2):  # rows: data idx, cols: fsdp
    assert len({d.process_index for d in row}) == 1, (
        "fsdp row must be intra-process", mesh.devices)
assert {d.process_index for d in mesh.devices.reshape(2, 2)[:, 0]} \
    == {0, 1}, "data axis must span the process boundary"

init_fn, loss_fn = lm_task(cfg, mesh=mesh)
trainer = Trainer(
    init_fn=init_fn, loss_fn=loss_fn, tx=optax.adam(1e-2), mesh=mesh,
    metrics=MetricsLogger(stream=open(os.devnull, "w")),
)
state = trainer.create_state(seed=0)
# FSDP actually shards the weights: each param's embed dim is split
# over the fsdp axis, so every train step all-gathers weights inside
# each process while grads cross processes over the data axis.
wq = state.params["layers"]["attn"]["wq"]
assert "fsdp" in tuple(str(a) for a in wq.sharding.spec), wq.sharding.spec

# Global batch 8 = 2 processes x 4 local rows; each process feeds only
# its local shard (batch axis = data axis = process axis).
rng = np.random.RandomState(env.process_id)


def data():
    while True:
        yield {"tokens": rng.randint(0, 64, size=(4, 16)).astype(np.int32)}


state = trainer.fit(data(), num_steps=3, state=state,
                    examples_per_step=8, log_every=0)
print(f"SHARDED process={env.process_id} "
      f"loss={trainer.last_metrics['loss']:.6f} "
      f"step={int(state.step)}", flush=True)
"""


def test_two_process_rendezvous_and_psum():
    outs = _run_two_workers(_WORKER, "rendezvous-test", 150)
    # 1.0 + 2.0 over the two processes.
    assert "RENDEZVOUS process=0 sum=3.0" in outs[0][1], outs[0]
    assert "RENDEZVOUS process=1 sum=3.0" in outs[1][1], outs[1]


def test_two_process_training_through_trainer():
    """REAL multi-host SPMD training in CI: two OS processes, the
    shipped Trainer.fit, each feeding only its process-local batch shard
    (make_array_from_process_local_data), gradients averaged by compiled
    collectives over the distributed backend.  Both processes must end
    at the identical replicated loss — the multi-worker contract the
    reference could only check on rented clusters (SURVEY.md §4)."""
    outs = _run_two_workers(_TRAIN_WORKER, "train-rendezvous", 240)
    lines = [next(ln for ln in out.splitlines() if ln.startswith("TRAIN"))
             for _, out, _ in outs]
    # Same replicated state on both processes, steps advanced.
    loss0 = lines[0].split("loss=")[1].split()[0]
    loss1 = lines[1].split("loss=")[1].split()[0]
    assert loss0 == loss1, lines
    assert "step=3" in lines[0], lines


def test_two_process_two_device_sharded_training():
    """Multi-process x multi-device mesh in CI (VERDICT r4 item 6): two
    OS processes x two CPU devices each, a data x fsdp mesh whose DATA
    axis spans the process boundary and whose FSDP axis shards weights
    intra-process — the topology of a real multi-host slice — through
    the shipped Trainer.fit to the identical replicated loss."""
    outs = _run_two_workers(
        _SHARDED_TRAIN_WORKER, "sharded-rendezvous", 300,
        devices_per_process=2)
    lines = [next(ln for ln in out.splitlines()
                  if ln.startswith("SHARDED"))
             for _, out, _ in outs]
    loss0 = lines[0].split("loss=")[1].split()[0]
    loss1 = lines[1].split("loss=")[1].split()[0]
    assert loss0 == loss1, lines
    assert "step=3" in lines[0], lines


def test_late_jax_platforms_override_warns(monkeypatch, caplog):
    """ADVICE r5: once JAX backends are materialized, the
    `jax_platforms` update in initialize() is silently a no-op — the
    CPU fake-slice run it defends against would land on the real chip
    with zero signal.  initialize() must detect the already-built
    backends and warn loudly."""
    import logging

    import jax

    from kubeflow_tpu.runtime import bootstrap

    jax.devices()  # materialize backends before initialize() runs
    assert bootstrap._backends_already_initialized()
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    with caplog.at_level(logging.WARNING,
                         logger="kubeflow_tpu.runtime.bootstrap"):
        bootstrap.initialize(bootstrap.worker_env({}))
    assert any("cannot take effect" in r.getMessage()
               for r in caplog.records), caplog.records
