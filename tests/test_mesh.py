"""Mesh / sharding-rule tests on the 8-device fake slice."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec

from kubeflow_tpu.parallel import (
    DATA,
    FSDP,
    SEQUENCE,
    TENSOR,
    MeshSpec,
    batch_sharding,
    logical_spec,
    named_sharding,
)
from kubeflow_tpu.runtime.topology import fake_slice


class TestMeshSpec:
    def test_infer_data_axis(self):
        spec = MeshSpec(tensor=2)
        assert spec.sizes(8)[DATA] == 4

    def test_explicit_sizes_must_multiply(self):
        with pytest.raises(ValueError, match="slots"):
            MeshSpec(data=3, tensor=2).sizes(8)

    def test_nonpositive_size_rejected(self):
        with pytest.raises(ValueError, match="positive"):
            MeshSpec(tensor=0).sizes(8)
        with pytest.raises(ValueError, match="positive"):
            MeshSpec(tensor=-2).sizes(8)

    def test_two_wildcards_rejected(self):
        with pytest.raises(ValueError, match="at most one"):
            MeshSpec(data=-1, fsdp=-1).sizes(8)

    def test_indivisible_rejected(self):
        with pytest.raises(ValueError, match="divide"):
            MeshSpec(tensor=3).sizes(8)

    def test_build_full_axes(self, devices):
        mesh = MeshSpec(data=2, sequence=2, tensor=2).build(devices)
        assert mesh.shape == {
            DATA: 2, FSDP: 1, "pipeline": 1, "expert": 1, SEQUENCE: 2, TENSOR: 2,
        }
        assert mesh.devices.size == 8

    def test_topology_mismatch(self, devices):
        with pytest.raises(ValueError, match="expects"):
            MeshSpec().build(devices, topology=fake_slice(16))


class TestLogicalRules:
    def test_transformer_kernel_spec(self):
        # Column-parallel MLP kernel: embed over fsdp, mlp over tensor.
        assert logical_spec(("embed", "mlp")) == PartitionSpec(FSDP, TENSOR)

    def test_activation_spec(self):
        spec = logical_spec(("batch", "seq", "act_embed"))
        assert spec == PartitionSpec((DATA, FSDP), SEQUENCE)

    def test_duplicate_mesh_axis_degrades(self):
        # vocab and heads both map to tensor; second use degrades to None.
        assert logical_spec(("vocab", "heads")) == PartitionSpec(TENSOR)

    def test_unknown_axis_unsharded(self):
        assert logical_spec(("mystery", "mlp")) == PartitionSpec(None, TENSOR)

    def test_trailing_nones_trimmed(self):
        assert logical_spec(("mlp", "norm")) == PartitionSpec(TENSOR)


class TestShardedCompute:
    def test_batch_sharded_matmul_runs(self, devices):
        mesh = MeshSpec(data=4, tensor=2).build(devices)
        x = jnp.ones((8, 16))
        w = jnp.ones((16, 32))
        xs = jax.device_put(x, batch_sharding(mesh))
        ws = jax.device_put(w, named_sharding(mesh, (None, "embed")))

        @jax.jit
        def f(x, w):
            return x @ w

        out = f(xs, ws)
        np.testing.assert_allclose(np.asarray(out), np.full((8, 32), 16.0))
        # Output batch dim stays sharded over data.
        assert out.sharding.spec[0] in ((DATA, FSDP), DATA)

    def test_psum_over_mesh_axis(self, devices):
        mesh = MeshSpec(data=8).build(devices)

        @jax.jit
        def total(x):
            return jax.shard_map(
                lambda v: jax.lax.psum(v, DATA),
                mesh=mesh,
                in_specs=PartitionSpec(DATA),
                out_specs=PartitionSpec(),
            )(x)

        x = jnp.arange(8.0)
        np.testing.assert_allclose(np.asarray(total(x)), np.full((1,), 28.0))
