"""Bootstrap installer tests (heir of bootstrap/.../server_test.go)."""

import yaml

from kubeflow_tpu.tools.bootstrap import BootConfig, render


def test_default_config_renders_platform():
    cfg = BootConfig(platform="generic")
    objs = render(cfg)
    kinds = [o["kind"] for o in objs]
    assert kinds[0] == "Namespace"
    assert "CustomResourceDefinition" in kinds  # operator CRD
    assert kinds.count("Deployment") >= 2


def test_gke_platform_adds_admin_binding_and_cloud_param():
    cfg = BootConfig(platform="gke")
    objs = render(cfg)
    assert objs[-1]["kind"] == "ClusterRoleBinding"
    assert objs[-1]["roleRef"]["name"] == "cluster-admin"


def test_yaml_config_roundtrip(tmp_path):
    path = tmp_path / "boot.yaml"
    path.write_text(yaml.safe_dump({
        "bootstrap": {
            "namespace": "ml",
            "platform": "generic",
            "components": [
                {"prototype": "tpujob-operator", "name": "op"},
                {"prototype": "tpu-job", "name": "train",
                 "params": {"slice_type": "v5p-32"}},
            ],
        },
    }))
    cfg = BootConfig.load(path)
    assert cfg.namespace == "ml"
    objs = render(cfg)
    assert objs[0]["metadata"]["name"] == "ml"
    tpujob = [o for o in objs if o["kind"] == "TPUJob"][0]
    assert tpujob["spec"]["sliceType"] == "v5p-32"
