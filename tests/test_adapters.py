"""Adapter-array multi-model serving (§5.11): stacked per-tenant
deltas, one SPMD program, co-batched variants.

The contract under test, layer by layer:

  - REGISTRY: bounded slots, digest-verified load, LRU eviction of
    IDLE adapters only (in-flight pins are untouchable), a per-adapter
    breaker so a corrupt artifact can't hot-loop the loader while the
    last-good revision keeps serving, typed 404/429 sheds.
  - ENGINE IDENTITY: a mixed-adapter continuous batch is bit-identical
    to per-adapter sequential runs — through plain decode, adapter-
    scoped prefix-cache hits, speculative decode, and a tensor mesh —
    while ``compiled_programs()`` never grows a per-adapter entry.
  - WIRE: ``model@adapter`` resolves through ModelServer to the engine
    (predict + streaming), unknown adapters shed 404, and a request
    naming an adapter can never silently fall through to base weights.

Heavy combined sweeps carry ``slow``; every contract keeps a cheap
tier-1 sibling.
"""

import threading

import numpy as np
import pytest

SEED = 20260807
VOCAB, NEW_TOKENS = 96, 10
RANK = 4


# ---------------------------------------------------------------------------
# fixtures


@pytest.fixture(scope="module")
def lm():
    """Tiny LM (dims divide tensor=2) + single-request greedy
    reference for BASE traffic; adapter references come from
    sequential engine runs (generate() has no adapter surface)."""
    import jax
    from flax import linen as nn

    from kubeflow_tpu.models.generate import DecodeConfig, generate
    from kubeflow_tpu.models.transformer import Transformer
    from kubeflow_tpu.serving.loaders import _model_config

    cfg = _model_config({
        "vocab_size": VOCAB, "d_model": 32, "n_layers": 2,
        "n_heads": 4, "n_kv_heads": 2, "d_ff": 64, "head_dim": 8,
        "max_seq_len": 64, "dtype": "float32"})
    model = Transformer(cfg)
    params = nn.unbox(model.init(
        jax.random.key(SEED), np.zeros((1, 8), np.int32))["params"])
    decode = DecodeConfig(max_new_tokens=NEW_TOKENS, temperature=0.0)
    cache = {}

    def reference(prompt):
        key = np.asarray(prompt, np.int32).tobytes()
        if key not in cache:
            out, _ = generate(cfg, params,
                              np.asarray(prompt, np.int32)[None],
                              decode)
            cache[key] = np.asarray(out)[0].tolist()
        return cache[key]

    return cfg, params, decode, reference


def _cfg():
    from kubeflow_tpu.serving.loaders import _model_config

    return _model_config({
        "vocab_size": VOCAB, "d_model": 32, "n_layers": 2,
        "n_heads": 4, "n_kv_heads": 2, "d_ff": 64, "head_dim": 8,
        "max_seq_len": 64, "dtype": "float32"})


def _factors(cfg, seed):
    from kubeflow_tpu.serving.adapters import random_adapter_factors

    # scale=0.5: large enough that the delta flips greedy argmax on a
    # 32-dim toy model — a variant that decodes base's exact tokens
    # would make every identity assertion vacuous.
    return random_adapter_factors(cfg, RANK, seed, scale=0.5)


def _registry(cfg, names=("alpha", "beta"), **kw):
    from kubeflow_tpu.serving.adapters import AdapterRegistry

    kw.setdefault("slots", 4)
    kw.setdefault("rank", RANK)
    reg = AdapterRegistry(cfg, **kw)
    for i, name in enumerate(names):
        reg.put(name, _factors(cfg, SEED + 100 + i))
    return reg


def _engine(lm, **kw):
    from kubeflow_tpu.serving.engine import DecodeEngine

    cfg, params, decode, _ = lm
    kw.setdefault("slots", 3)
    kw.setdefault("prefill_len", 16)
    kw.setdefault("prefill_chunk_tokens", 4)
    kw.setdefault("kv_block_tokens", 4)
    return DecodeEngine(cfg, dict(params), decode, **kw)


def _prompts(n=4, seed_off=0):
    rng = np.random.RandomState(SEED + seed_off)
    return [rng.randint(1, VOCAB, size=(k,)).astype(np.int32)
            for k in (8, 5, 11, 16, 3, 9)[:n]]


def _sequential_refs(lm, workload, **engine_kw):
    """Per-adapter sequential goldens: ONE request in flight at a
    time on a fresh engine — the baseline co-batching must match."""
    engine_kw.setdefault("adapters", _registry(lm[0]))
    engine_kw.setdefault("name", "ad-seq-ref")
    eng = _engine(lm, **engine_kw)
    try:
        refs = []
        for adapter, prompt, new in workload:
            req = {"tokens": prompt, "max_new_tokens": new}
            if adapter:
                req["adapter"] = adapter
            refs.append(eng.submit(req)["tokens"][0].tolist())
        return refs
    finally:
        eng.close()


def _counting_proxy(fn, compiles, key):
    class _Proxy:
        def lower(self, *a, **kw):
            compiles[key] += 1
            return fn.lower(*a, **kw)

        def __call__(self, *a, **kw):
            return fn(*a, **kw)

    return _Proxy()


def _mixed_workload(n_each=2):
    prompts = _prompts(6, seed_off=3)
    workload = []
    for i, adapter in enumerate((None, "alpha", "beta") * n_each):
        workload.append((adapter, prompts[i % len(prompts)],
                         3 + (i % 3) * 3))
    return workload


def _run_concurrent(eng, workload):
    outs = [None] * len(workload)

    def client(i):
        adapter, prompt, new = workload[i]
        req = {"tokens": prompt, "max_new_tokens": new}
        if adapter:
            req["adapter"] = adapter
        try:
            outs[i] = eng.submit(req)["tokens"][0].tolist()
        except Exception as exc:  # noqa: BLE001 — surfaced by assert
            outs[i] = exc
    threads = [threading.Thread(target=client, args=(i,))
               for i in range(len(workload))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    return outs


# ---------------------------------------------------------------------------
# host side: registry, artifacts, breaker


class TestAdapterRegistry:
    def test_split_model_adapter(self):
        from kubeflow_tpu.serving.adapters import split_model_adapter

        assert split_model_adapter("lm") == ("lm", None)
        assert split_model_adapter("lm@t1") == ("lm", "t1")
        assert split_model_adapter("lm@") == ("lm", None)

    def test_stack_shapes_base_row_zero(self):
        from kubeflow_tpu.serving.adapters import init_adapter_stack

        cfg = _cfg()
        stack = init_adapter_stack(cfg, rows=3, rank=RANK)
        wq_a = stack["attn"]["wq_a"]
        assert wq_a.shape == (3, cfg.n_layers, cfg.d_model, RANK)
        assert stack["mlp"]["wi_b"].shape == (
            3, cfg.n_layers, 2, RANK, cfg.d_ff)
        reg = _registry(cfg, names=("alpha",))
        stack, version = reg.stack_snapshot()
        assert version >= 1
        for leaves in stack.values():
            for arr in leaves.values():
                assert not np.any(arr[0])      # base row stays zero
        assert any(np.any(arr[1]) for leaves in stack.values()
                   for arr in leaves.values())  # alpha landed in row 1

    def test_save_load_roundtrip_digest_verified(self, tmp_path):
        import json

        from kubeflow_tpu.serving.adapters import (
            factors_digest,
            load_adapter,
            save_adapter,
        )

        cfg = _cfg()
        factors = _factors(cfg, SEED + 1)
        path = str(tmp_path / "t1.npz")
        digest = save_adapter(path, factors)
        assert digest == factors_digest(factors)
        loaded, got = load_adapter(path, cfg, RANK)
        assert got == digest
        np.testing.assert_array_equal(
            loaded["attn"]["wq_a"],
            np.asarray(factors["attn"]["wq_a"], np.float32))
        # Sidecar/content mismatch = torn or tampered artifact: refuse.
        (tmp_path / "t1.npz.json").write_text(
            json.dumps({"digest": "0" * 64}))
        with pytest.raises(ValueError, match="digest mismatch"):
            load_adapter(path, cfg, RANK)
        # Wrong-shaped artifact (e.g. exported at another rank): refuse.
        bad = str(tmp_path / "t2.npz")
        with open(bad, "wb") as f:
            np.savez(f, **{"attn/wq_a": np.zeros((1, 2), np.float32)})
        with pytest.raises(ValueError, match="missing/misshaped"):
            load_adapter(bad, cfg, RANK)

    def test_acquire_pins_release_unpins(self, tmp_path):
        from kubeflow_tpu.serving.adapters import (
            AdapterNotFound,
            AdapterRegistry,
            save_adapter,
        )

        cfg = _cfg()
        save_adapter(str(tmp_path / "a.npz"), _factors(cfg, SEED + 2))
        reg = AdapterRegistry(cfg, slots=2, rank=RANK,
                              directory=str(tmp_path), name="pins")
        idx, digest = reg.acquire("a")
        assert idx == 1 and len(digest) == 64
        assert reg.salt(idx) == bytes.fromhex(digest)
        assert reg.salt(0) == b""
        assert reg.loaded()[0]["pins"] == 1
        idx2, _ = reg.acquire("a")
        assert idx2 == idx
        assert reg.loaded()[0]["pins"] == 2
        reg.release(idx)
        reg.release(idx)
        assert reg.loaded()[0]["pins"] == 0
        assert reg.stats()["adapters_resident"] == 1
        with pytest.raises(AdapterNotFound):
            reg.acquire("ghost")
        # Wire names must not path-traverse out of the directory.
        with pytest.raises(AdapterNotFound):
            reg.acquire("../a")

    def test_lru_evicts_idle_only_all_pinned_sheds(self, tmp_path):
        from kubeflow_tpu.serving.adapters import (
            AdapterRegistry,
            save_adapter,
        )
        from kubeflow_tpu.serving.errors import Overloaded

        cfg = _cfg()
        for i, name in enumerate(("a", "b", "c", "d")):
            save_adapter(str(tmp_path / f"{name}.npz"),
                         _factors(cfg, SEED + 10 + i))
        reg = AdapterRegistry(cfg, slots=2, rank=RANK,
                              directory=str(tmp_path), name="lru")
        ia, _ = reg.acquire("a")            # pinned (in-flight)
        ib, _ = reg.acquire("b")
        reg.release(ib)                     # b idle -> the LRU victim
        ic, _ = reg.acquire("c")
        names = {r["name"] for r in reg.loaded()}
        assert names == {"a", "c"}, (
            "eviction must take the idle adapter, never a pinned one")
        with pytest.raises(Overloaded) as exc:
            reg.acquire("d")                # a and c both pinned
        assert exc.value.retry_after_s > 0
        reg.release(ia)
        reg.release(ic)
        idd, _ = reg.acquire("d")           # idle slot frees up
        assert idd in (ia, ic)

    def test_corrupt_artifact_breaker_last_good_serves(self, tmp_path):
        from kubeflow_tpu.serving.adapters import (
            AdapterRegistry,
            save_adapter,
        )
        from kubeflow_tpu.serving.errors import Overloaded
        from kubeflow_tpu.testing import faults

        cfg = _cfg()
        good = _factors(cfg, SEED + 20)
        save_adapter(str(tmp_path / "a.npz"), good)
        reg = AdapterRegistry(cfg, slots=2, rank=RANK,
                              directory=str(tmp_path), name="breaker")
        with faults.injected("seed=0") as inj:
            idx, digest = reg.acquire("a")
            reg.release(idx)
            assert inj.fired("adapter.load") == 1
            # Corrupt the artifact ON DISK (different bytes -> the
            # registry sees a changed digest and attempts a reload).
            (tmp_path / "a.npz").write_bytes(b"not an npz")
            (tmp_path / "a.npz.json").unlink()
            idx2, digest2 = reg.acquire("a")
            assert (idx2, digest2) == (idx, digest), (
                "last-good revision must keep serving through a "
                "corrupt reload")
            reg.release(idx2)
            assert inj.fired("adapter.load") == 2
            # Breaker open: the next acquire must NOT touch the loader.
            idx3, _ = reg.acquire("a")
            reg.release(idx3)
            assert inj.fired("adapter.load") == 2
            # A never-loaded corrupt adapter sheds typed 429 and the
            # open breaker keeps the loader cold on the retry.
            (tmp_path / "b.npz").write_bytes(b"garbage")
            with pytest.raises(Overloaded):
                reg.acquire("b")
            fired = inj.fired("adapter.load")
            with pytest.raises(Overloaded):
                reg.acquire("b")
            assert inj.fired("adapter.load") == fired
            # Backoff expiry (policy clock) + a repaired artifact:
            # the breaker closes and the load goes through.
            save_adapter(str(tmp_path / "b.npz"),
                         _factors(cfg, SEED + 21))
            inj.advance_clock(600)
            ib, _ = reg.acquire("b")
            reg.release(ib)
            assert {r["name"] for r in reg.loaded()} >= {"b"}

    def test_put_reloads_in_place(self):
        cfg = _cfg()
        reg = _registry(cfg, names=("alpha",))
        idx = reg.put("alpha", _factors(cfg, SEED + 30))
        assert idx == 1                     # same row, new revision
        _, version = reg.stack_snapshot()
        idx2 = reg.put("alpha", _factors(cfg, SEED + 31))
        assert idx2 == idx
        _, version2 = reg.stack_snapshot()
        assert version2 > version


# ---------------------------------------------------------------------------
# device side: co-batched identity, one program set


class TestAdapterEngineIdentity:
    def test_mixed_batch_matches_sequential_no_new_programs(
            self, lm, monkeypatch):
        """Base + alpha + beta co-batched through 3 slots must emit
        exactly the tokens each request gets when it runs ALONE, the
        base rows must equal single-request generate(), the variants
        must genuinely diverge from base — and the whole mixed
        workload compiles the same two programs base-only traffic
        does (the stacked gather is inside them, never beside them)."""
        from kubeflow_tpu.models import generate as gen_mod

        _, _, _, reference = lm
        workload = _mixed_workload()
        want = _sequential_refs(lm, workload)
        # Count compiles only for the co-batched engine under test
        # (the reference engine above did its own, identical, two).
        compiles = {"chunked_prefill": 0, "step": 0, "verify": 0}
        for attr, key in (("prefill_chunk_into_slot", "chunked_prefill"),
                          ("decode_step", "step"),
                          ("verify_step", "verify")):
            monkeypatch.setattr(gen_mod, attr, _counting_proxy(
                getattr(gen_mod, attr), compiles, key))
        eng = _engine(lm, adapters=_registry(lm[0]), name="ad-mixed")
        try:
            outs = _run_concurrent(eng, workload)
            for i, (adapter, prompt, new) in enumerate(workload):
                assert outs[i] == want[i], (
                    f"request {i} (adapter={adapter}) diverged from "
                    "its sequential run")
                if adapter is None:
                    assert outs[i] == reference(prompt)[
                        :len(prompt) + new], (
                        "co-batched base row drifted from generate()")
            by_key = {}
            for (adapter, prompt, _), out in zip(workload, outs):
                by_key[(adapter, prompt.tobytes())] = out
            for (adapter, pkey), out in by_key.items():
                if adapter is not None and (None, pkey) in by_key:
                    assert out != by_key[(None, pkey)], (
                        f"adapter {adapter} decoded base's exact "
                        "tokens — the delta never applied")
            stats = eng.stats()
            assert stats["requests"] == len(workload)
            assert stats["adapters"]["adapters_resident"] == 2
        finally:
            eng.close()
        two = {"chunked_prefill": 1, "step": 1, "verify": 0}
        assert compiles == two
        assert eng.compiled_programs() == two

    def test_prefix_cache_is_adapter_scoped(self, lm):
        """One prompt under base/alpha/beta, twice each, prefix cache
        ON: every rerun must hit ITS OWN adapter's chain and emit the
        cache-off sequential tokens — a cross-adapter alias would
        splice one tenant's KV into another's generation."""
        prompt = _prompts(1, seed_off=9)[0]
        workload = [(a, prompt, NEW_TOKENS)
                    for a in (None, "alpha", "beta")] * 2
        want = _sequential_refs(lm, workload, prefix_caching=False,
                                name="ad-nocache-ref")
        eng = _engine(lm, adapters=_registry(lm[0]),
                      prefix_caching=True, name="ad-scoped")
        try:
            for i, (adapter, _, new) in enumerate(workload):
                req = {"tokens": prompt, "max_new_tokens": new}
                if adapter:
                    req["adapter"] = adapter
                got = eng.submit(req)["tokens"][0].tolist()
                assert got == want[i], (
                    f"round {i} adapter={adapter}: cached pages "
                    "leaked across adapter scopes")
            stats = eng.stats()
            # Round 2 hits each scope's own published chain.
            assert stats["prefix_hits"] >= 3
        finally:
            eng.close()

    def test_speculative_identity(self, lm):
        """Draft/verify speculation over a mixed-adapter batch stays
        bit-identical to the non-speculative sequential runs (the
        verify program gathers the same per-slot delta)."""
        workload = _mixed_workload(n_each=1)
        want = _sequential_refs(lm, workload, name="ad-spec-ref")
        eng = _engine(lm, adapters=_registry(lm[0]),
                      speculative_tokens=3, name="ad-spec")
        try:
            outs = _run_concurrent(eng, workload)
            assert outs == want
            assert eng.compiled_programs()["verify"] == 1
        finally:
            eng.close()

    def test_mesh2_identity(self, lm):
        """The stacked adapter axis sharded over tensor=2 changes no
        token: mixed traffic equals the unsharded sequential runs."""
        from kubeflow_tpu.serving import sharding

        workload = _mixed_workload(n_each=1)
        want = _sequential_refs(lm, workload, name="ad-mesh-ref")
        eng = _engine(lm, adapters=_registry(lm[0]),
                      mesh=sharding.build_mesh({"tensor": 2}),
                      name="ad-mesh2")
        try:
            outs = _run_concurrent(eng, workload)
            assert outs == want
        finally:
            eng.close()

    @pytest.mark.slow  # ~9s combined sweep; the per-path tests above stay tier-1
    def test_full_sweep_spec_prefix_mesh(self, lm):
        """The heavy combination: speculation ON, prefix cache ON,
        tensor=2 mesh, 12 mixed requests over 3 slots with slot reuse
        and repeated prompts — every row equals its sequential twin."""
        from kubeflow_tpu.serving import sharding

        workload = _mixed_workload(n_each=4)
        want = _sequential_refs(lm, workload, name="ad-sweep-ref",
                                speculative_tokens=3)
        eng = _engine(lm, adapters=_registry(lm[0]),
                      mesh=sharding.build_mesh({"tensor": 2}),
                      speculative_tokens=3, prefix_caching=True,
                      name="ad-sweep")
        try:
            outs = _run_concurrent(eng, workload)
            assert outs == want
        finally:
            eng.close()

    def test_hot_load_evict_under_pinned_traffic(self, lm, tmp_path):
        """Slot pressure with a live pin: loading a third adapter into
        a 2-slot registry must evict the IDLE one, never the pinned
        one, and every accepted request decodes its correct tokens —
        including the re-load of the evicted adapter afterwards."""
        from kubeflow_tpu.serving.adapters import (
            AdapterRegistry,
            save_adapter,
        )

        cfg = lm[0]
        for i, name in enumerate(("alpha", "beta", "gamma")):
            save_adapter(str(tmp_path / f"{name}.npz"),
                         _factors(cfg, SEED + 100 + i))
        prompt = _prompts(1, seed_off=11)[0]
        workload = [(a, prompt, 6)
                    for a in ("alpha", "beta", "gamma", "beta")]
        want = _sequential_refs(
            lm, workload, name="ad-hot-ref",
            adapters=_registry(cfg, names=("alpha", "beta", "gamma")))
        reg = AdapterRegistry(cfg, slots=2, rank=RANK,
                              directory=str(tmp_path), name="ad-hot")
        eng = _engine(lm, adapters=reg, name="ad-hot")
        try:
            assert eng.submit({"tokens": prompt, "max_new_tokens": 6,
                               "adapter": "alpha"}
                              )["tokens"][0].tolist() == want[0]
            assert eng.submit({"tokens": prompt, "max_new_tokens": 6,
                               "adapter": "beta"}
                              )["tokens"][0].tolist() == want[1]
            # Pin alpha (a request mid-generation holds exactly this).
            pin, _ = reg.acquire("alpha")
            assert eng.submit({"tokens": prompt, "max_new_tokens": 6,
                               "adapter": "gamma"}
                              )["tokens"][0].tolist() == want[2]
            assert {r["name"] for r in reg.loaded()} == \
                {"alpha", "gamma"}, "eviction touched the pinned slot"
            reg.release(pin)
            # The evicted adapter hot-reloads on demand, identically.
            assert eng.submit({"tokens": prompt, "max_new_tokens": 6,
                               "adapter": "beta"}
                              )["tokens"][0].tolist() == want[3]
        finally:
            eng.close()

    def test_load_fault_mid_traffic(self, lm, tmp_path):
        """adapter.load raising mid-traffic: the named request sheds
        typed 429, the breaker keeps the loader cold on the retry,
        resident adapters keep serving bit-identically, and after the
        backoff the load goes through."""
        from kubeflow_tpu.serving.adapters import (
            AdapterRegistry,
            save_adapter,
        )
        from kubeflow_tpu.serving.errors import Overloaded
        from kubeflow_tpu.testing import faults

        cfg = lm[0]
        for i, name in enumerate(("alpha", "beta")):
            save_adapter(str(tmp_path / f"{name}.npz"),
                         _factors(cfg, SEED + 100 + i))
        prompt = _prompts(1, seed_off=13)[0]
        workload = [("alpha", prompt, 6), ("beta", prompt, 6)]
        want = _sequential_refs(lm, workload, name="ad-fault-ref")
        reg = AdapterRegistry(cfg, slots=2, rank=RANK,
                              directory=str(tmp_path), name="ad-fault")
        eng = _engine(lm, adapters=reg, name="ad-fault")
        try:
            # Warm alpha before the fault window: the scripted raise
            # must hit beta's cold load, not resident traffic.
            assert eng.submit(
                {"tokens": prompt, "max_new_tokens": 6,
                 "adapter": "alpha"}
            )["tokens"][0].tolist() == want[0]
            with faults.injected("adapter.load:raise*1") as inj:
                with pytest.raises(Overloaded):
                    eng.submit({"tokens": prompt, "max_new_tokens": 6,
                                "adapter": "beta"})
                assert inj.fired("adapter.load") == 1
                # Breaker open: the retry sheds WITHOUT a load attempt.
                with pytest.raises(Overloaded):
                    eng.submit({"tokens": prompt, "max_new_tokens": 6,
                                "adapter": "beta"})
                assert inj.fired("adapter.load") == 1
                # The resident adapter is untouched by the fault.
                assert eng.submit(
                    {"tokens": prompt, "max_new_tokens": 6,
                     "adapter": "alpha"}
                )["tokens"][0].tolist() == want[0]
                inj.advance_clock(600)      # breaker backoff expires
                assert eng.submit(
                    {"tokens": prompt, "max_new_tokens": 6,
                     "adapter": "beta"}
                )["tokens"][0].tolist() == want[1]
        finally:
            eng.close()

    def test_unknown_adapter_and_no_registry_shed_404(self, lm):
        from kubeflow_tpu.serving.adapters import AdapterNotFound

        prompt = _prompts(1)[0]
        bare = _engine(lm, name="ad-bare")
        try:
            with pytest.raises(AdapterNotFound):
                bare.submit({"tokens": prompt, "adapter": "alpha"})
        finally:
            bare.close()
        eng = _engine(lm, adapters=_registry(lm[0]), name="ad-404")
        try:
            with pytest.raises(AdapterNotFound):
                eng.submit({"tokens": prompt, "adapter": "ghost"})
            # The shed left nothing pinned or in flight.
            stats = eng.stats()
            assert stats["in_flight_requests"] == 0
            assert stats["adapters"]["adapters_pinned"] == 0
        finally:
            eng.close()


# ---------------------------------------------------------------------------
# wire: model@adapter through ModelServer


@pytest.fixture(scope="module")
def adapter_server(tmp_path_factory, lm):
    """An exported lm served through the engine batching plane with an
    adapter directory beside it: the full ``model@adapter`` wire."""
    import jax

    from kubeflow_tpu.models.transformer import Transformer
    from kubeflow_tpu.serving.adapters import save_adapter
    from kubeflow_tpu.serving.export import export
    from kubeflow_tpu.serving.main import batcher_factory
    from kubeflow_tpu.serving.model_server import ModelServer

    overrides = {
        "vocab_size": VOCAB, "d_model": 32, "n_layers": 2, "n_heads": 4,
        "n_kv_heads": 2, "d_ff": 64, "head_dim": 8, "max_seq_len": 64,
        "dtype": "float32"}
    model = Transformer(lm[0])
    variables = model.init(
        jax.random.key(SEED), np.zeros((1, 8), np.int32))
    base = tmp_path_factory.mktemp("adapter-models") / "lm"
    export(base, 1, variables,
           loader="kubeflow_tpu.serving.loaders:lm_generate",
           config={"model": overrides,
                   "max_new_tokens": NEW_TOKENS, "temperature": 0.0})
    adir = tmp_path_factory.mktemp("adapters")
    for i, name in enumerate(("alpha", "beta")):
        save_adapter(str(adir / f"{name}.npz"),
                     _factors(lm[0], SEED + 100 + i))
    server = ModelServer()
    server.add_model("lm", str(base))
    server.enable_batching("lm", batcher_factory(
        micro_batch_size=0, batch_timeout_s=0.005, lm_engine=True,
        lm_engine_slots=2, lm_engine_prefill_len=16,
        prefill_chunk_tokens=4, kv_block_tokens=4,
        adapters_dir=str(adir), adapter_slots=4, adapter_rank=RANK))
    yield server
    server.stop()


class TestModelAdapterRouting:
    def test_predict_resolves_adapter_and_matches_engine(
            self, lm, adapter_server):
        prompt = _prompts(1, seed_off=17)[0]
        want = _sequential_refs(
            lm, [("alpha", prompt, NEW_TOKENS),
                 (None, prompt, NEW_TOKENS)], name="ad-wire-ref")
        out = adapter_server.predict(
            "lm@alpha", {"tokens": prompt[None]})
        assert np.asarray(out["tokens"])[0].tolist() == want[0]
        base = adapter_server.predict("lm", {"tokens": prompt[None]})
        assert np.asarray(base["tokens"])[0].tolist() == want[1]
        assert want[0] != want[1]

    def test_unknown_adapter_is_404(self, adapter_server):
        from kubeflow_tpu.serving.adapters import AdapterNotFound

        prompt = _prompts(1)[0]
        with pytest.raises(AdapterNotFound):  # KeyError -> HTTP 404
            adapter_server.predict("lm@ghost",
                                   {"tokens": prompt[None]})
        with pytest.raises(KeyError):
            adapter_server.predict("nope@alpha",
                                   {"tokens": prompt[None]})

    def test_has_model_and_readyz_advertisement(self, adapter_server):
        assert adapter_server.has_model("lm@anything")
        info = adapter_server.adapter_info()
        names = {a["name"] for a in info.get("lm", ())}
        assert "alpha" in names
        digests = {a["digest"] for a in info["lm"]}
        assert all(len(d) == 64 for d in digests)

    def test_generate_stream_carries_adapter(self, lm, adapter_server):
        prompt = _prompts(1, seed_off=19)[0]
        want = _sequential_refs(
            lm, [("beta", prompt, NEW_TOKENS)], name="ad-stream-ref")
        meta, stream = adapter_server.generate_stream(
            "lm@beta", {"tokens": prompt})
        toks = []
        for chunk in stream:
            toks.extend(chunk)
        assert meta["resumable"]
        assert prompt.tolist() + toks == want[0]

    def test_direct_path_never_serves_base_for_adapter(self, lm,
                                                       tmp_path):
        """A model WITHOUT the engine plane must refuse model@adapter
        (404), not silently decode base weights for a tenant."""
        import jax

        from kubeflow_tpu.models.transformer import Transformer
        from kubeflow_tpu.serving.adapters import AdapterNotFound
        from kubeflow_tpu.serving.export import export
        from kubeflow_tpu.serving.model_server import ModelServer

        overrides = {
            "vocab_size": VOCAB, "d_model": 32, "n_layers": 2,
            "n_heads": 4, "n_kv_heads": 2, "d_ff": 64, "head_dim": 8,
            "max_seq_len": 64, "dtype": "float32"}
        model = Transformer(lm[0])
        variables = model.init(
            jax.random.key(SEED), np.zeros((1, 8), np.int32))
        base = tmp_path / "lm"
        export(base, 1, variables,
               loader="kubeflow_tpu.serving.loaders:lm_generate",
               config={"model": overrides, "max_new_tokens": 4,
                       "temperature": 0.0})
        server = ModelServer()
        server.add_model("lm", str(base))
        try:
            prompt = _prompts(1)[0]
            with pytest.raises(AdapterNotFound):
                server.predict("lm@alpha", {"tokens": prompt[None]})
        finally:
            server.stop()
