"""Data pipeline tests: format roundtrip, native core vs python fallback,
shuffle, sharding, batching."""

import numpy as np
import pytest

from kubeflow_tpu.data.loader import (
    RecordDataset,
    RecordWriter,
    decode_example,
    encode_example,
    read_records,
    tensor_batches,
    write_example_shards,
    _native_lib,
)


@pytest.fixture(scope="module")
def shard_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("records")
    examples = [
        {"x": np.full((4,), i, np.float32), "y": np.int64(i)}
        for i in range(100)
    ]
    paths = write_example_shards(examples, d, examples_per_shard=25)
    return d, paths


class TestFormat:
    def test_roundtrip(self, tmp_path):
        p = tmp_path / "a.kftr"
        with RecordWriter(p) as w:
            w.write(b"hello")
            w.write(b"")
            w.write(b"\x00" * 1000)
        assert list(read_records(p)) == [b"hello", b"", b"\x00" * 1000]

    def test_bad_magic_rejected(self, tmp_path):
        p = tmp_path / "bad.bin"
        p.write_bytes(b"GARBAGE")
        with pytest.raises(IOError, match="magic"):
            list(read_records(p))

    def test_example_codec(self):
        ex = {"image": np.arange(12, dtype=np.float32).reshape(3, 4),
              "label": np.int64(7)}
        out = decode_example(encode_example(ex))
        np.testing.assert_array_equal(out["image"], ex["image"])
        assert out["label"] == 7


class TestNativeCore:
    def test_native_lib_builds(self):
        assert _native_lib() is not None, "g++ toolchain expected in image"

    def test_native_matches_python(self, shard_dir):
        _, paths = shard_dir
        native = sorted(RecordDataset(paths, num_threads=3))
        python = sorted(RecordDataset(paths, force_python=True))
        assert native == python
        assert len(native) == 100

    def test_shuffle_changes_order_keeps_multiset(self, shard_dir):
        _, paths = shard_dir
        plain = list(RecordDataset(paths, num_threads=1))
        shuffled = list(RecordDataset(paths, num_threads=1,
                                      shuffle_buffer=64, seed=7))
        assert sorted(plain) == sorted(shuffled)
        assert plain != shuffled

    def test_repeat(self, shard_dir):
        _, paths = shard_dir
        twice = list(RecordDataset([paths[0]], repeat=2))
        assert len(twice) == 50

    def test_error_surfaces(self, tmp_path):
        p = tmp_path / "trunc.kftr"
        with RecordWriter(p) as w:
            w.write(b"full record")
        # Truncate mid-payload.
        data = p.read_bytes()
        p.write_bytes(data[:-4])
        # Same IOError contract from both readers: the default (python
        # auto-select) and the explicitly threaded native core.
        with pytest.raises(IOError, match="truncated"):
            list(RecordDataset([p]))
        with pytest.raises(IOError, match="truncated"):
            list(RecordDataset([p], num_threads=1))


class TestSharding:
    def test_processes_partition_files(self, shard_dir):
        _, paths = shard_dir
        ds = RecordDataset(paths)
        seen = []
        for pid in range(2):
            seen += list(ds.shard(pid, 2))
        assert sorted(seen) == sorted(RecordDataset(paths))

    def test_too_few_files_raises(self, shard_dir):
        _, paths = shard_dir
        with pytest.raises(ValueError, match="no files"):
            RecordDataset([paths[0]]).shard(1, 2)


class TestTrainCnnFromShards:
    @pytest.mark.slow  # ~22s CNN train; the readers have direct tests above
    def test_train_cnn_reads_kftr(self, tmp_path):
        """train_cnn --data-dir: the full CNN entrypoint trains from KFTR
        shards through the loader (heir of tf_cnn_benchmarks' real-data
        mode, tf-controller-examples/tf-cnn/create_job_specs.py:98-119)."""
        from kubeflow_tpu.tools.train_cnn import main

        examples = [
            {"image": np.random.RandomState(i).randn(8, 8, 3).astype(
                np.float32),
             "label": np.int64(i % 4)}
            for i in range(64)
        ]
        write_example_shards(examples, tmp_path, examples_per_shard=16)
        rc = main([
            "--model", "resnet18", "--steps", "2",
            "--batch-size-per-device", "1", "--image-size", "8",
            "--num-classes", "4", "--dtype", "float32",
            "--data-dir", str(tmp_path), "--shuffle-buffer", "0",
            "--data-threads", "2", "--log-every", "1",
        ])
        assert rc == 0

    def test_train_cnn_no_shards_fails_cleanly(self, tmp_path):
        from kubeflow_tpu.tools.train_cnn import main

        assert main(["--steps", "1", "--data-dir", str(tmp_path)]) == 1


class TestLoaderThroughput:
    def test_native_core_keeps_up(self, tmp_path):
        """The native core exists to out-feed the chip; this smoke pins
        that it at least sustains multi-shard reads at a sane rate and
        does not regress below the single-thread python fallback on a
        parallel read (bench.py --model=data reports the real numbers)."""
        import time

        payload = b"x" * 65536
        paths = []
        for s in range(4):
            p = tmp_path / f"{s}.kftr"
            with RecordWriter(p) as w:
                for _ in range(64):
                    w.write(payload)
            paths.append(p)

        def rate(**kw):
            t0 = time.perf_counter()
            n = sum(1 for _ in RecordDataset(paths, **kw))
            return n / (time.perf_counter() - t0)

        native = rate(num_threads=4)
        assert rate(force_python=True) > 0  # fallback functional
        assert native > 1000, f"native core too slow: {native:.0f} rec/s"


class TestBatching:
    def test_trainer_shaped_batches(self, shard_dir):
        _, paths = shard_dir
        batches = list(tensor_batches(RecordDataset(paths), 32))
        assert len(batches) == 3  # 100 // 32, remainder dropped
        assert batches[0]["x"].shape == (32, 4)
        assert batches[0]["y"].shape == (32,)

    def test_keep_remainder(self, shard_dir):
        _, paths = shard_dir
        batches = list(tensor_batches(RecordDataset(paths), 32,
                                      drop_remainder=False))
        assert batches[-1]["x"].shape == (4, 4)


class TestStackedBatches:
    """In-core decode + batch assembly (loader.stacked_batches): the
    pipeline default, where the C++ core fills per-key batch buffers
    numpy wraps zero-copy."""

    def test_matches_python_pipeline_exactly(self, shard_dir):
        _, paths = shard_dir
        # num_threads=1 => deterministic file/record order, comparable
        # element-for-element with the sequential python path.
        nat = list(RecordDataset(paths, num_threads=1)
                   .stacked_batches(32))
        py = list(tensor_batches(
            RecordDataset(paths, force_python=True), 32))
        assert len(nat) == len(py) == 3
        for a, b in zip(nat, py):
            assert set(a) == set(b)
            for k in a:
                np.testing.assert_array_equal(a[k], b[k])
                assert a[k].dtype == b[k].dtype

    def test_threaded_same_multiset(self, shard_dir):
        _, paths = shard_dir
        nat = list(RecordDataset(paths, num_threads=4)
                   .stacked_batches(10, drop_remainder=False))
        ys = np.sort(np.concatenate([b["y"] for b in nat]))
        py = list(tensor_batches(
            RecordDataset(paths, force_python=True), 10,
            drop_remainder=False))
        ys_py = np.sort(np.concatenate([b["y"] for b in py]))
        np.testing.assert_array_equal(ys, ys_py)

    def test_remainder(self, shard_dir):
        _, paths = shard_dir
        nat = list(RecordDataset(paths, num_threads=1)
                   .stacked_batches(32, drop_remainder=False))
        assert [b["y"].shape[0] for b in nat] == [32, 32, 32, 4]

    def test_schema_mismatch_raises(self, tmp_path):
        from kubeflow_tpu.data.loader import RecordWriter, encode_example

        p = tmp_path / "mixed.kftr"
        with RecordWriter(p) as w:
            w.write(encode_example({"x": np.zeros(4, np.float32)}))
            w.write(encode_example({"x": np.zeros(5, np.float32)}))
        with pytest.raises(IOError, match="schema"):
            list(RecordDataset([p]).stacked_batches(2))

    def test_non_kte1_payload_falls_back(self, tmp_path):
        from kubeflow_tpu.data.loader import RecordWriter

        import io as _io

        p = tmp_path / "npz.kftr"
        buf = _io.BytesIO()
        np.savez(buf, x=np.arange(4, dtype=np.float32))
        with RecordWriter(p) as w:
            for _ in range(4):
                w.write(buf.getvalue())
        batches = list(RecordDataset([p]).stacked_batches(2))
        assert len(batches) == 2
        assert batches[0]["x"].shape == (2, 4)

    def test_uint8_dtype_roundtrips(self, tmp_path):
        """1-byte dtypes serialize as '|u1' — the '|' must not break
        schema parsing (uint8 images are the serving wire format)."""
        from kubeflow_tpu.data.loader import write_example_shards

        img = np.arange(48, dtype=np.uint8).reshape(4, 4, 3)
        paths = write_example_shards(
            ({"image": img + i, "ok": np.bool_(i % 2)} for i in range(6)),
            tmp_path, examples_per_shard=6)
        (batch,) = RecordDataset(paths, num_threads=1).stacked_batches(6)
        assert batch["image"].dtype == np.uint8
        assert batch["ok"].dtype == np.bool_
        np.testing.assert_array_equal(batch["image"][2], img + 2)

    def test_scalar_fields_stack_to_vector(self, tmp_path):
        from kubeflow_tpu.data.loader import write_example_shards

        paths = write_example_shards(
            ({"label": np.int64(i)} for i in range(8)),
            tmp_path, examples_per_shard=8)
        (batch,) = RecordDataset(paths, num_threads=1).stacked_batches(8)
        np.testing.assert_array_equal(batch["label"], np.arange(8))

    def test_truncated_shard_raises_not_truncates(self, tmp_path):
        """A corrupt shard must raise from the stacked path exactly as
        it does from raw iteration — silent short batches would train
        on partial data (review finding r3)."""
        from kubeflow_tpu.data.loader import RecordWriter, encode_example

        p = tmp_path / "trunc.kftr"
        with RecordWriter(p) as w:
            for i in range(64):
                w.write(encode_example({"x": np.full(8, i, np.float32)}))
        data = p.read_bytes()
        p.write_bytes(data[:-7])  # cut mid-payload
        with pytest.raises(IOError, match="truncated"):
            list(RecordDataset([p], num_threads=1).stacked_batches(64))

    def test_nbytes_shape_mismatch_rejected(self, tmp_path):
        """A record whose nbytes disagrees with shape x dtype must be
        rejected at schema lock-in — the fill path sizes buffers from
        shape x dtype and copies nbytes (heap overflow otherwise)."""
        import struct as st

        from kubeflow_tpu.data.loader import RecordWriter

        # Hand-craft KTE1: key 'x', dtype '<f4', shape (4,), but
        # nbytes=64 with 64 payload bytes (parse succeeds, sizes lie).
        payload = (b"KTE1" + st.pack("<H", 1)
                   + st.pack("<HH", 1, 3) + b"x" + b"<f4"
                   + st.pack("<B", 1) + st.pack("<q", 4)
                   + st.pack("<Q", 64) + b"\0" * 64)
        p = tmp_path / "evil.kftr"
        with RecordWriter(p) as w:
            for _ in range(4):
                w.write(payload)
        with pytest.raises((IOError, ValueError)):
            list(RecordDataset([p], num_threads=1).stacked_batches(4))

    def test_reserved_key_characters_rejected_at_encode(self):
        from kubeflow_tpu.data.loader import encode_example

        with pytest.raises(ValueError, match="reserved"):
            encode_example({"a|b": np.zeros(2, np.float32)})
        with pytest.raises(ValueError, match="reserved"):
            encode_example({"a;b": np.zeros(2, np.float32)})

    def test_foreign_shard_with_separator_key_falls_back(self, tmp_path):
        """A shard written by a foreign producer with a '|' in a key:
        the native schema path refuses it and stacked_batches falls back
        to the python decode loop, which handles it."""
        import struct as st

        from kubeflow_tpu.data.loader import RecordWriter

        arr = np.arange(4, dtype=np.float32)
        payload = (b"KTE1" + st.pack("<H", 1)
                   + st.pack("<HH", 3, 3) + b"a|b" + b"<f4"
                   + st.pack("<B", 1) + st.pack("<q", 4)
                   + st.pack("<Q", 16) + arr.tobytes())
        p = tmp_path / "foreign.kftr"
        with RecordWriter(p) as w:
            for _ in range(4):
                w.write(payload)
        (batch,) = RecordDataset([p]).stacked_batches(4)
        assert batch["a|b"].shape == (4, 4)
        np.testing.assert_array_equal(batch["a|b"][0], arr)

    def test_shuffle_composes(self, shard_dir):
        _, paths = shard_dir
        nat = list(RecordDataset(paths, num_threads=1, shuffle_buffer=64,
                                 seed=3).stacked_batches(
                                     10, drop_remainder=False))
        plain = list(RecordDataset(paths, num_threads=1)
                     .stacked_batches(10, drop_remainder=False))
        ys = np.concatenate([b["y"] for b in nat])
        ys_plain = np.concatenate([b["y"] for b in plain])
        assert not np.array_equal(ys, ys_plain)
        np.testing.assert_array_equal(np.sort(ys), np.sort(ys_plain))


class TestSeekResume:
    """tensor_batches.seek — the resume fast-path Trainer.fit probes
    for: a decode-free header-walk skip for unshuffled record datasets
    (the reference's era had no resume at all; fit's contract is
    'rerun the same command')."""

    def test_seek_matches_slicing(self, shard_dir):
        # force_python: the fast header-walk skip applies only to the
        # file-ordered python reader (the threaded native core
        # interleaves files, so native datasets drain on seek).
        _, paths = shard_dir  # 100 examples over 4 files of 25
        full = list(tensor_batches(
            RecordDataset(paths, force_python=True), 8))
        for n in (0, 1, 3, 7):  # incl. skips crossing file boundaries
            it = tensor_batches(
                RecordDataset(paths, force_python=True), 8)
            it.seek(n)
            got = list(it)
            assert len(got) == len(full) - n, (n, len(got))
            np.testing.assert_array_equal(got[0]["x"], full[n]["x"])
            np.testing.assert_array_equal(got[-1]["y"], full[-1]["y"])

    def test_seek_across_epochs(self, shard_dir):
        _, paths = shard_dir
        full = list(tensor_batches(
            RecordDataset(paths, repeat=2, force_python=True), 8))
        it = tensor_batches(
            RecordDataset(paths, repeat=2, force_python=True), 8)
        it.seek(13)  # crosses into the second epoch
        got = list(it)
        np.testing.assert_array_equal(got[0]["x"], full[13]["x"])

    def test_seek_past_end_yields_nothing(self, shard_dir):
        _, paths = shard_dir
        it = tensor_batches(RecordDataset(paths, force_python=True), 8)
        it.seek(999)
        assert list(it) == []

    def test_native_dataset_seek_drains_consistently(self, shard_dir):
        """Native (threaded) datasets drain on seek; the resumed stream
        must still be the same LENGTH as a slice (content order is the
        native core's own)."""
        _, paths = shard_dir
        full = list(tensor_batches(RecordDataset(paths), 8))
        it = tensor_batches(RecordDataset(paths), 8)
        it.seek(5)
        assert len(list(it)) == len(full) - 5

    def test_shuffled_dataset_falls_back_to_drain(self, shard_dir):
        _, paths = shard_dir
        ds = RecordDataset(paths, shuffle_buffer=16, force_python=True)
        full = list(tensor_batches(
            RecordDataset(paths, shuffle_buffer=16, force_python=True),
            8))
        it = tensor_batches(ds, 8)
        it.seek(2)
        got = list(it)
        # Same shuffle seed: drain-skip reproduces the same stream.
        assert len(got) == len(full) - 2
        np.testing.assert_array_equal(got[0]["x"], full[2]["x"])

    def test_fit_uses_seek_on_resume(self, shard_dir, tmp_path):
        """End to end: Trainer.fit resumes from a checkpoint and seeks
        the dataset instead of replaying decoded batches."""
        import jax
        import jax.numpy as jnp
        import optax

        from kubeflow_tpu.parallel import MeshSpec
        from kubeflow_tpu.runtime.checkpoint import CheckpointManager
        from kubeflow_tpu.runtime.metrics import MetricsLogger
        from kubeflow_tpu.runtime.train import Trainer

        _, paths = shard_dir

        def init_fn(rng):
            return {"w": jnp.zeros((4,))}, {}

        def loss_fn(params, mutable, batch, rng):
            pred = batch["x"].astype(jnp.float32) @ params["w"]
            loss = jnp.mean((pred - batch["y"].astype(jnp.float32)) ** 2)
            return loss, ({}, {})

        def make_trainer():
            return Trainer(
                init_fn=init_fn, loss_fn=loss_fn, tx=optax.sgd(1e-3),
                mesh=MeshSpec(data=1).build(jax.devices()[:1]),
                checkpoints=CheckpointManager(str(tmp_path / "ck")),
                checkpoint_every=4,
                metrics=MetricsLogger(stream=open("/dev/null", "w")),
            )

        t1 = make_trainer()
        t1.fit(tensor_batches(RecordDataset(paths), 8), num_steps=4,
               log_every=0)
        # Second run resumes at step 4; seek must be the path taken.
        seeks = []
        data = tensor_batches(RecordDataset(paths), 8)
        orig_seek = data.seek
        data.seek = lambda n: (seeks.append(n), orig_seek(n))[1]
        t2 = make_trainer()
        t2.fit(data, num_steps=8, log_every=0)
        assert seeks == [4], seeks


class TestTransientRetry:
    """data.next hook: transient read errors retry with capped jittered
    backoff on the policy clock; budget exhaustion raises DataError."""

    def test_injected_faults_retried_to_success(self, shard_dir):
        from kubeflow_tpu.data.loader import DataError  # noqa: F401
        from kubeflow_tpu.testing import faults

        _, paths = shard_dir
        ds = RecordDataset(paths, force_python=True)
        want = [b["y"].tolist() for b in tensor_batches(ds, 10)]
        with faults.injected(
                "data.next:raise*3;data.next:skew=100"):
            got = [b["y"].tolist()
                   for b in tensor_batches(ds, 10, retries=4)]
        assert got == want  # stream re-aligned past yielded batches

    def test_mid_stream_fault_does_not_duplicate_batches(
            self, shard_dir):
        from kubeflow_tpu.testing import faults

        _, paths = shard_dir
        ds = RecordDataset(paths, force_python=True)
        want = [b["y"].tolist() for b in tensor_batches(ds, 10)]
        # Fault fires on the 4th pull only (3 clean encounters first,
        # via times-bounded skew entries consuming nothing).
        with faults.injected("seed=1;data.next:raise=0*1@0.35;"
                             "data.next:skew=100"):
            got = [b["y"].tolist()
                   for b in tensor_batches(ds, 10, retries=4)]
        assert got == want

    def test_budget_exhaustion_raises_typed_error(self, shard_dir):
        from kubeflow_tpu.data.loader import DataError
        from kubeflow_tpu.testing import faults

        _, paths = shard_dir
        ds = RecordDataset(paths, force_python=True)
        with faults.injected("data.next:raise;data.next:skew=100"):
            with pytest.raises(DataError) as exc:
                list(tensor_batches(ds, 10, retries=2))
        assert isinstance(exc.value.__cause__, faults.FaultInjected)

    def test_real_io_error_is_transient(self, tmp_path):
        """A shard that becomes readable between attempts (flaky
        mount) recovers without DataError."""
        from kubeflow_tpu.testing import faults

        examples = [{"x": np.full((2,), i, np.int32)}
                    for i in range(8)]
        paths = write_example_shards(examples, tmp_path,
                                     examples_per_shard=8)
        good = paths[0].read_bytes()
        paths[0].write_bytes(good[:9])  # truncated: IOError on read
        ds = RecordDataset(paths, force_python=True)
        tb = tensor_batches(ds, 4, retries=3)
        orig_wait = tb._retry_wait

        def heal_then_wait(attempt):
            paths[0].write_bytes(good)  # the mount comes back
            orig_wait(attempt)

        tb._retry_wait = heal_then_wait
        with faults.injected("data.next:skew=100"):
            out = list(tb)
        total = sum(b["x"].shape[0] for b in out)
        assert total == 8
        assert [b["x"][0, 0] for b in out] == [0, 4]  # no duplicates

    def test_retry_budget_is_consecutive(self, shard_dir):
        """A success resets the budget: N scattered faults with budget
        < N still complete."""
        from kubeflow_tpu.testing import faults

        _, paths = shard_dir
        ds = RecordDataset(paths, force_python=True)
        want = [b["y"].tolist() for b in tensor_batches(ds, 10)]
        with faults.injected("seed=3;data.next:raise@0.3;"
                             "data.next:skew=100"):
            got = [b["y"].tolist()
                   for b in tensor_batches(ds, 10, retries=2)]
        assert got == want

    def test_one_shot_iterable_propagates_raw(self, shard_dir):
        """A plain generator dataset cannot be rebuilt+realigned —
        the fault propagates unretried (no silent batch drops); the
        supervisor's per-attempt data_factory owns recovery there."""
        from kubeflow_tpu.testing import faults

        _, paths = shard_dir
        payloads = list(RecordDataset(paths, force_python=True))

        def gen():
            yield from payloads

        with faults.injected("data.next:raise*1"):
            with pytest.raises(faults.FaultInjected):
                list(tensor_batches(gen(), 10, retries=5))
