"""Data pipeline tests: format roundtrip, native core vs python fallback,
shuffle, sharding, batching."""

import numpy as np
import pytest

from kubeflow_tpu.data.loader import (
    RecordDataset,
    RecordWriter,
    decode_example,
    encode_example,
    read_records,
    tensor_batches,
    write_example_shards,
    _native_lib,
)


@pytest.fixture(scope="module")
def shard_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("records")
    examples = [
        {"x": np.full((4,), i, np.float32), "y": np.int64(i)}
        for i in range(100)
    ]
    paths = write_example_shards(examples, d, examples_per_shard=25)
    return d, paths


class TestFormat:
    def test_roundtrip(self, tmp_path):
        p = tmp_path / "a.kftr"
        with RecordWriter(p) as w:
            w.write(b"hello")
            w.write(b"")
            w.write(b"\x00" * 1000)
        assert list(read_records(p)) == [b"hello", b"", b"\x00" * 1000]

    def test_bad_magic_rejected(self, tmp_path):
        p = tmp_path / "bad.bin"
        p.write_bytes(b"GARBAGE")
        with pytest.raises(ValueError, match="magic"):
            list(read_records(p))

    def test_example_codec(self):
        ex = {"image": np.arange(12, dtype=np.float32).reshape(3, 4),
              "label": np.int64(7)}
        out = decode_example(encode_example(ex))
        np.testing.assert_array_equal(out["image"], ex["image"])
        assert out["label"] == 7


class TestNativeCore:
    def test_native_lib_builds(self):
        assert _native_lib() is not None, "g++ toolchain expected in image"

    def test_native_matches_python(self, shard_dir):
        _, paths = shard_dir
        native = sorted(RecordDataset(paths, num_threads=3))
        python = sorted(RecordDataset(paths, force_python=True))
        assert native == python
        assert len(native) == 100

    def test_shuffle_changes_order_keeps_multiset(self, shard_dir):
        _, paths = shard_dir
        plain = list(RecordDataset(paths, num_threads=1))
        shuffled = list(RecordDataset(paths, num_threads=1,
                                      shuffle_buffer=64, seed=7))
        assert sorted(plain) == sorted(shuffled)
        assert plain != shuffled

    def test_repeat(self, shard_dir):
        _, paths = shard_dir
        twice = list(RecordDataset([paths[0]], repeat=2))
        assert len(twice) == 50

    def test_error_surfaces(self, tmp_path):
        p = tmp_path / "trunc.kftr"
        with RecordWriter(p) as w:
            w.write(b"full record")
        # Truncate mid-payload.
        data = p.read_bytes()
        p.write_bytes(data[:-4])
        with pytest.raises(IOError, match="truncated"):
            list(RecordDataset([p]))


class TestSharding:
    def test_processes_partition_files(self, shard_dir):
        _, paths = shard_dir
        ds = RecordDataset(paths)
        seen = []
        for pid in range(2):
            seen += list(ds.shard(pid, 2))
        assert sorted(seen) == sorted(RecordDataset(paths))

    def test_too_few_files_raises(self, shard_dir):
        _, paths = shard_dir
        with pytest.raises(ValueError, match="no files"):
            RecordDataset([paths[0]]).shard(1, 2)


class TestTrainCnnFromShards:
    def test_train_cnn_reads_kftr(self, tmp_path):
        """train_cnn --data-dir: the full CNN entrypoint trains from KFTR
        shards through the loader (heir of tf_cnn_benchmarks' real-data
        mode, tf-controller-examples/tf-cnn/create_job_specs.py:98-119)."""
        from kubeflow_tpu.tools.train_cnn import main

        examples = [
            {"image": np.random.RandomState(i).randn(8, 8, 3).astype(
                np.float32),
             "label": np.int64(i % 4)}
            for i in range(64)
        ]
        write_example_shards(examples, tmp_path, examples_per_shard=16)
        rc = main([
            "--model", "resnet18", "--steps", "2",
            "--batch-size-per-device", "1", "--image-size", "8",
            "--num-classes", "4", "--dtype", "float32",
            "--data-dir", str(tmp_path), "--shuffle-buffer", "0",
            "--data-threads", "2", "--log-every", "1",
        ])
        assert rc == 0

    def test_train_cnn_no_shards_fails_cleanly(self, tmp_path):
        from kubeflow_tpu.tools.train_cnn import main

        assert main(["--steps", "1", "--data-dir", str(tmp_path)]) == 1


class TestLoaderThroughput:
    def test_native_core_keeps_up(self, tmp_path):
        """The native core exists to out-feed the chip; this smoke pins
        that it at least sustains multi-shard reads at a sane rate and
        does not regress below the single-thread python fallback on a
        parallel read (bench.py --model=data reports the real numbers)."""
        import time

        payload = b"x" * 65536
        paths = []
        for s in range(4):
            p = tmp_path / f"{s}.kftr"
            with RecordWriter(p) as w:
                for _ in range(64):
                    w.write(payload)
            paths.append(p)

        def rate(**kw):
            t0 = time.perf_counter()
            n = sum(1 for _ in RecordDataset(paths, **kw))
            return n / (time.perf_counter() - t0)

        native = rate(num_threads=4)
        assert rate(force_python=True) > 0  # fallback functional
        assert native > 1000, f"native core too slow: {native:.0f} rec/s"


class TestBatching:
    def test_trainer_shaped_batches(self, shard_dir):
        _, paths = shard_dir
        batches = list(tensor_batches(RecordDataset(paths), 32))
        assert len(batches) == 3  # 100 // 32, remainder dropped
        assert batches[0]["x"].shape == (32, 4)
        assert batches[0]["y"].shape == (32,)

    def test_keep_remainder(self, shard_dir):
        _, paths = shard_dir
        batches = list(tensor_batches(RecordDataset(paths), 32,
                                      drop_remainder=False))
        assert batches[-1]["x"].shape == (4, 4)
