"""Data pipeline tests: format roundtrip, native core vs python fallback,
shuffle, sharding, batching."""

import numpy as np
import pytest

from kubeflow_tpu.data.loader import (
    RecordDataset,
    RecordWriter,
    decode_example,
    encode_example,
    read_records,
    tensor_batches,
    write_example_shards,
    _native_lib,
)


@pytest.fixture(scope="module")
def shard_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("records")
    examples = [
        {"x": np.full((4,), i, np.float32), "y": np.int64(i)}
        for i in range(100)
    ]
    paths = write_example_shards(examples, d, examples_per_shard=25)
    return d, paths


class TestFormat:
    def test_roundtrip(self, tmp_path):
        p = tmp_path / "a.kftr"
        with RecordWriter(p) as w:
            w.write(b"hello")
            w.write(b"")
            w.write(b"\x00" * 1000)
        assert list(read_records(p)) == [b"hello", b"", b"\x00" * 1000]

    def test_bad_magic_rejected(self, tmp_path):
        p = tmp_path / "bad.bin"
        p.write_bytes(b"GARBAGE")
        with pytest.raises(ValueError, match="magic"):
            list(read_records(p))

    def test_example_codec(self):
        ex = {"image": np.arange(12, dtype=np.float32).reshape(3, 4),
              "label": np.int64(7)}
        out = decode_example(encode_example(ex))
        np.testing.assert_array_equal(out["image"], ex["image"])
        assert out["label"] == 7


class TestNativeCore:
    def test_native_lib_builds(self):
        assert _native_lib() is not None, "g++ toolchain expected in image"

    def test_native_matches_python(self, shard_dir):
        _, paths = shard_dir
        native = sorted(RecordDataset(paths, num_threads=3))
        python = sorted(RecordDataset(paths, force_python=True))
        assert native == python
        assert len(native) == 100

    def test_shuffle_changes_order_keeps_multiset(self, shard_dir):
        _, paths = shard_dir
        plain = list(RecordDataset(paths, num_threads=1))
        shuffled = list(RecordDataset(paths, num_threads=1,
                                      shuffle_buffer=64, seed=7))
        assert sorted(plain) == sorted(shuffled)
        assert plain != shuffled

    def test_repeat(self, shard_dir):
        _, paths = shard_dir
        twice = list(RecordDataset([paths[0]], repeat=2))
        assert len(twice) == 50

    def test_error_surfaces(self, tmp_path):
        p = tmp_path / "trunc.kftr"
        with RecordWriter(p) as w:
            w.write(b"full record")
        # Truncate mid-payload.
        data = p.read_bytes()
        p.write_bytes(data[:-4])
        with pytest.raises(IOError, match="truncated"):
            list(RecordDataset([p]))


class TestSharding:
    def test_processes_partition_files(self, shard_dir):
        _, paths = shard_dir
        ds = RecordDataset(paths)
        seen = []
        for pid in range(2):
            seen += list(ds.shard(pid, 2))
        assert sorted(seen) == sorted(RecordDataset(paths))

    def test_too_few_files_raises(self, shard_dir):
        _, paths = shard_dir
        with pytest.raises(ValueError, match="no files"):
            RecordDataset([paths[0]]).shard(1, 2)


class TestBatching:
    def test_trainer_shaped_batches(self, shard_dir):
        _, paths = shard_dir
        batches = list(tensor_batches(RecordDataset(paths), 32))
        assert len(batches) == 3  # 100 // 32, remainder dropped
        assert batches[0]["x"].shape == (32, 4)
        assert batches[0]["y"].shape == (32,)

    def test_keep_remainder(self, shard_dir):
        _, paths = shard_dir
        batches = list(tensor_batches(RecordDataset(paths), 32,
                                      drop_remainder=False))
        assert batches[-1]["x"].shape == (4, 4)
