"""Fused multi-step decode (models/generate.py ``decode_rounds`` +
serving/engine.py ``decode_rounds > 1``, docs §5.2e): the while_loop
round program must be INVISIBLE in the tokens — fused(k=8) ==
unfused(k=1) == single-request generate() across slot reuse, EOS
inside a round, deadline expiry at a round boundary, mid-round
admission, speculation-ON mixed traffic, and SPMD meshes — while the
fused engine compiles exactly ONE extra program (and the k=1 path
compiles none)."""

import dataclasses
import threading
import time

import numpy as np
import pytest

from kubeflow_tpu.serving.errors import DeadlineExceeded
from kubeflow_tpu.testing import faults

SEED = 20260730
VOCAB, PROMPT_LEN, NEW_TOKENS = 128, 8, 12


@pytest.fixture(scope="module")
def engine_model():
    """The same tiny LM config test_lm_serving's engines run, built
    directly (no export/ModelServer round trip — the engines take
    cfg/params/decode, and the full-suite jit cache already holds this
    config's generate() programs): yields (spec, None) in the
    engine_spec shape."""
    import jax
    from flax import linen as nn

    from kubeflow_tpu.models.generate import DecodeConfig
    from kubeflow_tpu.models.transformer import Transformer
    from kubeflow_tpu.serving.loaders import _model_config

    cfg = _model_config({
        "vocab_size": VOCAB, "d_model": 32, "n_layers": 2, "n_heads": 4,
        "n_kv_heads": 2, "d_ff": 64, "head_dim": 8, "max_seq_len": 64,
        "dtype": "float32"})
    model = Transformer(cfg)
    params = nn.unbox(model.init(
        jax.random.key(SEED), np.zeros((1, PROMPT_LEN), np.int32))
        ["params"])
    decode = DecodeConfig(max_new_tokens=NEW_TOKENS, temperature=0.0)
    yield {"cfg": cfg, "params": params, "decode": decode}, None


def _counting_proxy(fn, compiles, key):
    """Each .lower() call — exactly one XLA compilation in the
    AOT-disciplined engine — bumps ``compiles[key]``."""
    class _Proxy:
        def lower(self, *a, **kw):
            compiles[key] += 1
            return fn.lower(*a, **kw)

        def __call__(self, *a, **kw):
            return fn(*a, **kw)

    return _Proxy()


def _reference_rows(spec, prompts, news, decode=None):
    """Single-request generate() goldens truncated to each request's
    budget (greedy is prefix-stable)."""
    from kubeflow_tpu.models.generate import generate

    rows = []
    for prompt, new in zip(prompts, news):
        out, _ = generate(spec["cfg"], spec["params"],
                          np.asarray(prompt, np.int32)[None],
                          decode or spec["decode"])
        rows.append(np.asarray(out)[0, :len(prompt) + new].tolist())
    return rows


def _run_engine(spec, prompts, news, *, decode_rounds, slots=3,
                decode=None, name="test-fused", **kw):
    from kubeflow_tpu.serving.engine import DecodeEngine

    engine = DecodeEngine(
        spec["cfg"], spec["params"], decode or spec["decode"],
        slots=slots, prefill_len=16, admit_width=2,
        prefill_chunk_tokens=8, kv_block_tokens=4,
        decode_rounds=decode_rounds,
        name=f"{name}-k{decode_rounds}", **kw)
    try:
        outs = [None] * len(prompts)

        def client(i):
            outs[i] = engine.submit({
                "tokens": np.asarray(prompts[i], np.int32),
                "max_new_tokens": news[i]})

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(len(prompts))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return outs, engine.stats(), engine.compiled_programs()
    finally:
        engine.close()


class TestFusedDecode:
    def test_fused_matches_generate_slot_reuse_one_extra_program(
            self, engine_model, monkeypatch):
        """The tentpole identity: 9 mixed-length requests through 3
        slots (every slot reused, multi-chunk prefill, mid-round
        admission waves) are token-identical across fused(k=8),
        unfused(k=1), and generate() — and across BOTH engines the
        only programs compiled are one chunked prefill each, one step
        (the k=1 engine), and one fused round program (the k=8 engine,
        whose adaptive widths all ride the same executable)."""
        from kubeflow_tpu.models import generate as gen_mod

        compiles = {"chunked_prefill": 0, "step": 0, "verify": 0,
                    "decode_rounds": 0}
        for attr, key in (("prefill_chunk_into_slot", "chunked_prefill"),
                          ("decode_step", "step"),
                          ("verify_step", "verify"),
                          ("decode_rounds", "decode_rounds")):
            monkeypatch.setattr(gen_mod, attr, _counting_proxy(
                getattr(gen_mod, attr), compiles, key))

        spec, _ = engine_model
        rng = np.random.RandomState(SEED)
        lens = [3, 9, 16, 2, 9, 16, 3, 16, 2]
        news = [12, 6, 3, 8, 12, 4, 10, 5, 12]
        prompts = [rng.randint(1, VOCAB, size=(n,)).tolist()
                   for n in lens]
        want = _reference_rows(spec, prompts, news)

        fused_outs, fused_stats, fused_programs = _run_engine(
            spec, prompts, news, decode_rounds=8)
        plain_outs, _, plain_programs = _run_engine(
            spec, prompts, news, decode_rounds=1)
        for i in range(len(prompts)):
            got_f = np.asarray(fused_outs[i]["tokens"])[0].tolist()
            got_p = np.asarray(plain_outs[i]["tokens"])[0].tolist()
            assert got_f == want[i], (
                f"fused request {i} (len {lens[i]}, budget {news[i]}) "
                "drifted from single-request generate()")
            assert got_p == want[i], (
                f"k=1 request {i} drifted from generate()")

        # Fused rounds really ran, and the round-width accounting
        # surfaced through stats.
        assert fused_stats["decode_rounds"] == 8
        assert fused_stats["fused_rounds"] > 0
        assert fused_stats["steps_per_round_p50"] >= 1
        assert fused_stats["steps_per_round_p99"] \
            >= fused_stats["steps_per_round_p50"]
        assert fused_stats["fused_steps_wasted"] >= 0
        assert fused_stats["tokens"] == sum(news)
        assert fused_stats["active_slots"] == 0
        assert fused_stats["in_flight_requests"] == 0

        # Compile counts: the fused engine never builds the per-step
        # program; the k=1 engine never builds the fused one.
        assert compiles == {"chunked_prefill": 2, "step": 1,
                            "verify": 0, "decode_rounds": 1}
        assert fused_programs == {"chunked_prefill": 1, "step": 0,
                                  "verify": 0, "decode_rounds": 1}
        assert plain_programs == {"chunked_prefill": 1, "step": 1,
                                  "verify": 0}

    def test_eos_inside_round_matches_generate(self, engine_model):
        """A slot whose EOS lands mid-round freezes on device; the
        drain must deliver exactly generate()'s tokens up to and
        including EOS and the slot must come back."""
        from kubeflow_tpu.models.generate import generate
        from kubeflow_tpu.serving.engine import DecodeEngine

        spec, _ = engine_model
        rng = np.random.RandomState(SEED + 1)
        decode = dataclasses.replace(spec["decode"], eos_token=5)
        prompts = [rng.randint(1, VOCAB, size=(n,)).tolist()
                   for n in (3, 9, 16)]
        engine = DecodeEngine(spec["cfg"], spec["params"], decode,
                              slots=2, prefill_len=16, decode_rounds=8,
                              name="fused-eos")
        try:
            for prompt in prompts:
                out = engine.submit(
                    {"tokens": np.asarray(prompt, np.int32)})
                got = np.asarray(out["tokens"])[0, len(prompt):].tolist()
                ref, _ = generate(spec["cfg"], spec["params"],
                                  np.asarray(prompt, np.int32)[None],
                                  decode)
                ref = np.asarray(ref)[0, len(prompt):].tolist()
                if 5 in ref:
                    ref = ref[:ref.index(5) + 1]
                assert got == ref
            assert engine.stats()["active_slots"] == 0
        finally:
            engine.close()

    def test_deadline_expiry_at_round_boundary_frees_slot(
            self, engine_model):
        """Deadline enforcement under fused rounds is round-granular
        (§5.2e): a request expiring while a round is in flight is
        retired at the next boundary — DeadlineExceeded to the client,
        slot reclaimed for a successor whose tokens match generate()."""
        from kubeflow_tpu.serving.engine import DecodeEngine

        spec, _ = engine_model
        rng = np.random.RandomState(SEED)
        prompt_c = rng.randint(1, VOCAB, size=(6,)).tolist()
        prompt_a = rng.randint(1, VOCAB, size=(5,)).tolist()
        prompt_b = rng.randint(1, VOCAB, size=(7,)).tolist()
        # One fused round costs >= 200 ms (the injected step sleep
        # fires once per DISPATCH); A's 100 ms deadline expires during
        # the first round it could ride, so the boundary sweep must
        # retire it — its budget (12 tokens > 8-wide round) guarantees
        # it cannot complete inside one round.
        with faults.injected("seed=1;engine.step:sleep=0.2"):
            engine = DecodeEngine(spec["cfg"], spec["params"],
                                  spec["decode"], slots=2,
                                  prefill_len=16, decode_rounds=8,
                                  name="fused-dl")
            outs: dict = {}

            def client(key, prompt, deadline=None):
                try:
                    outs[key] = engine.submit(
                        {"tokens": np.asarray(prompt, np.int32)},
                        deadline=deadline)
                except Exception as exc:  # noqa: BLE001 — the point
                    outs[key] = exc

            try:
                t_c = threading.Thread(
                    target=client, args=("c", prompt_c))
                t_c.start()
                t_a = threading.Thread(
                    target=client, args=("a", prompt_a,
                                         faults.monotonic() + 0.1))
                t_a.start()
                t_a.join(timeout=60)
                assert isinstance(outs["a"], DeadlineExceeded), outs["a"]
                # B admitted into A's reclaimed slot while C decodes.
                client("b", prompt_b)
                t_c.join(timeout=60)
                stats = engine.stats()
                assert stats["deadline_expired"] == 1
                assert stats["in_flight_requests"] == 0
            finally:
                engine.close()
        want = _reference_rows(spec, [prompt_c, prompt_b],
                               [NEW_TOKENS, NEW_TOKENS])
        for key, ref in (("c", want[0]), ("b", want[1])):
            got = np.asarray(outs[key]["tokens"])[0].tolist()
            assert got == ref, (
                f"request {key!r} drifted after round-boundary expiry")

    def test_mid_round_admission_joins_at_boundary(self, engine_model):
        """A request arriving while a fused round is in flight joins
        at the next boundary and decodes identically to generate()."""
        from kubeflow_tpu.serving.engine import DecodeEngine

        spec, _ = engine_model
        rng = np.random.RandomState(SEED + 3)
        prompt_a = rng.randint(1, VOCAB, size=(9,)).tolist()
        prompt_b = rng.randint(1, VOCAB, size=(4,)).tolist()
        want = _reference_rows(spec, [prompt_a, prompt_b],
                               [NEW_TOKENS, NEW_TOKENS])
        engine = DecodeEngine(spec["cfg"], spec["params"],
                              spec["decode"], slots=2, prefill_len=16,
                              decode_rounds=8, name="fused-admit")
        try:
            outs: dict = {}

            def client(key, prompt):
                outs[key] = engine.submit(
                    {"tokens": np.asarray(prompt, np.int32)})

            t_a = threading.Thread(target=client, args=("a", prompt_a))
            t_a.start()
            time.sleep(0.02)  # A is mid-generation when B arrives
            client("b", prompt_b)
            t_a.join(timeout=60)
            for key, ref in (("a", want[0]), ("b", want[1])):
                got = np.asarray(outs[key]["tokens"])[0].tolist()
                assert got == ref, f"request {key!r} drifted"
        finally:
            engine.close()

    def test_spec_on_mixed_traffic_identity(self, engine_model,
                                            monkeypatch):
        """Speculation + fused rounds coexist: draft-ahead verify
        rounds interleave with fused decode rounds and the mixed
        repetitive/random workload stays token-identical to
        generate()."""
        import kubeflow_tpu.serving.engine as eng_mod

        # Zero the measured-throughput margin so gating never vetoes
        # verify rounds on a loaded box — identity is what is under
        # test, and it must hold regardless of gating.
        monkeypatch.setattr(eng_mod, "_SPEC_RATE_MARGIN", 0.0)

        spec, _ = engine_model
        rng = np.random.RandomState(SEED + 21)
        prompts, news = [], []
        for i in range(8):
            if i % 2 == 0:
                pat = rng.randint(1, VOCAB, size=(4,))
                prompts.append(np.tile(pat, 3).tolist())
            else:
                prompts.append(
                    rng.randint(1, VOCAB, size=(10,)).tolist())
            news.append([12, 8, 10, 6][i % 4])
        want = _reference_rows(spec, prompts, news)
        outs, stats, programs = _run_engine(
            spec, prompts, news, decode_rounds=8, slots=2,
            speculative_tokens=4, name="fused-spec")
        for i in range(len(prompts)):
            got = np.asarray(outs[i]["tokens"])[0].tolist()
            assert got == want[i], (
                f"spec-ON fused request {i} drifted from generate()")
        assert stats["fused_rounds"] > 0
        assert programs["decode_rounds"] == 1

    @pytest.mark.parametrize("tensor", [2])
    def test_mesh_fused_identity(self, engine_model, tensor):
        """Fused rounds compile SPMD under the serving mesh exactly
        like decode_step: greedy identity holds at mesh 2 (the
        conftest forces an 8-device CPU host platform; the mesh-1 /
        single-device fused path is every other test in this file)."""
        from kubeflow_tpu.serving import sharding
        from kubeflow_tpu.serving.engine import DecodeEngine

        spec, _ = engine_model
        rng = np.random.RandomState(SEED + 5)
        prompts = [rng.randint(1, VOCAB, size=(n,)).tolist()
                   for n in (8, 5, 11)]
        want = _reference_rows(spec, prompts, [NEW_TOKENS] * 3)
        mesh = sharding.build_mesh({"tensor": tensor})
        engine = DecodeEngine(spec["cfg"], spec["params"],
                              spec["decode"], slots=2, prefill_len=16,
                              kv_block_tokens=4, decode_rounds=8,
                              mesh=mesh, name=f"fused-mesh{tensor}")
        try:
            for i, prompt in enumerate(prompts):
                got = engine.submit(
                    {"tokens": np.asarray(prompt, np.int32)}
                )["tokens"][0].tolist()
                assert got == want[i], (
                    f"mesh={tensor} fused decode diverged on {i}")
            stats = engine.stats()
            assert stats["mesh_devices"] == max(1, tensor)
            assert stats["fused_rounds"] > 0
            assert engine.compiled_programs()["decode_rounds"] == 1
        finally:
            engine.close()

    def test_fault_inside_fused_round_aborts_cleanly(
            self, engine_model, monkeypatch):
        """A device fault inside a fused round (seeded at the
        engine.step chaos site, which _fused_round fires per dispatch)
        must error EVERY waiter — no hung client, no wedged loop."""
        from kubeflow_tpu.models import generate as gen_mod
        from kubeflow_tpu.serving.engine import DecodeEngine

        real = gen_mod.decode_rounds
        calls = {"n": 0}

        class _DiesOnSecondRound:
            def lower(self, *a, **kw):
                lowered = real.lower(*a, **kw)

                class _Lowered:
                    def compile(self_l):
                        exe = lowered.compile()

                        def run(*ra, **rkw):
                            calls["n"] += 1
                            if calls["n"] >= 2:
                                raise RuntimeError("device died")
                            return exe(*ra, **rkw)

                        return run

                return _Lowered()

        monkeypatch.setattr(gen_mod, "decode_rounds",
                            _DiesOnSecondRound())
        spec, _ = engine_model
        engine = DecodeEngine(spec["cfg"], spec["params"],
                              spec["decode"], slots=2, prefill_len=16,
                              decode_rounds=4, name="fused-abort")
        outs: dict = {}

        def client(i, new):
            try:
                outs[i] = engine.submit({
                    "tokens": np.arange(1, 5, dtype=np.int32),
                    "max_new_tokens": new})
            except Exception as exc:  # noqa: BLE001 — the point
                outs[i] = exc

        threads = [threading.Thread(target=client, args=a)
                   for a in ((0, 12), (1, 12))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert not any(t.is_alive() for t in threads), (
            "a client hung after the fused loop died")
        assert len(outs) == 2  # every waiter resolved (result or error)
        assert any(isinstance(v, Exception) for v in outs.values())
        engine.close()
