"""Tests for slice topologies and the TPUJob spec model."""

import pytest

from kubeflow_tpu.operator.crd import (
    MeshSpec,
    SpecError,
    TPUJobSpec,
    WorkerSpec,
)
from kubeflow_tpu.runtime.topology import (
    fake_slice,
    get_topology,
    list_topologies,
    parse_slice_type,
)


class TestTopology:
    def test_v5p_32_baseline_slice(self):
        topo = get_topology("v5p-32")
        assert topo.chips == 16
        assert topo.hosts == 4
        assert topo.chips_per_host == 4
        assert topo.ici_mesh == (2, 2, 4)

    def test_v5e_8_single_host(self):
        topo = get_topology("v5e-8")
        assert topo.hosts == 1 and topo.chips == 8

    def test_parse_mesh_form(self):
        assert parse_slice_type("v5e-4x4").name == "v5e-16"

    def test_unknown_raises(self):
        with pytest.raises(ValueError, match="unknown slice type"):
            get_topology("v99-1")

    def test_node_selector_targets_tpu(self):
        sel = get_topology("v5p-32").k8s_node_selector()
        assert sel["cloud.google.com/gke-tpu-accelerator"] == "tpu-v5p-slice"
        assert sel["cloud.google.com/gke-tpu-topology"] == "2x2x4"

    def test_registry_nonempty(self):
        assert "v5e-8" in list_topologies()

    def test_fake_slice(self):
        assert fake_slice(8).chips == 8


class TestMeshSpec:
    def test_wildcard_resolution(self):
        sizes = MeshSpec(data=-1, model=2).resolve(16)
        assert sizes["data"] == 8 and sizes["model"] == 2

    def test_exact_match(self):
        sizes = MeshSpec(data=4, model=2, sequence=2).resolve(16)
        assert sizes == {"data": 4, "fsdp": 1, "pipeline": 1, "model": 2,
                         "sequence": 2, "expert": 1}

    def test_mismatch_raises(self):
        with pytest.raises(SpecError, match="devices"):
            MeshSpec(data=3).resolve(16)

    def test_two_wildcards_raise(self):
        with pytest.raises(SpecError, match="-1"):
            MeshSpec(data=-1, model=-1).resolve(16)

    def test_zero_axis_rejected(self):
        with pytest.raises(SpecError, match=">= 1"):
            MeshSpec(model=0).resolve(8)

    def test_negative_axis_rejected(self):
        with pytest.raises(SpecError, match=">= 1"):
            MeshSpec(data=4, model=-2).resolve(8)


class TestTPUJobSpec:
    def test_worker_count_derived_from_slice(self):
        job = TPUJobSpec(name="j", slice_type="v5p-32")
        assert job.num_workers == 4       # one pod per slice host
        assert job.num_devices == 16

    def test_multislice(self):
        job = TPUJobSpec(name="j", slice_type="v5p-32", num_slices=2)
        assert job.num_workers == 8 and job.num_devices == 32

    def test_invalid_mesh_rejected_at_admission(self):
        with pytest.raises(SpecError):
            TPUJobSpec(name="j", slice_type="v5e-8",
                       mesh=MeshSpec(data=3, model=1))

    def test_cr_roundtrip(self):
        job = TPUJobSpec(
            name="train", slice_type="v5e-16",
            mesh=MeshSpec(data=-1, model=4),
            worker=WorkerSpec(image="me:1", args=["--steps=5"]),
        )
        cr = job.to_custom_resource()
        back = TPUJobSpec.from_custom_resource(cr)
        assert back.name == "train"
        assert back.mesh.model == 4
        assert back.worker.args == ["--steps=5"]
        assert back.topology.chips == 16

    def test_pipeline_axis_in_cr(self):
        """PP is a first-class mesh axis in the job spec: declared,
        validated against the slice at admission, round-tripped."""
        job = TPUJobSpec(
            name="pp", slice_type="v5e-16",
            mesh=MeshSpec(data=-1, pipeline=2),
            worker=WorkerSpec(image="me:1"),
        )
        assert job.mesh.resolve(16)["pipeline"] == 2
        back = TPUJobSpec.from_custom_resource(job.to_custom_resource())
        assert back.mesh.pipeline == 2
        with pytest.raises(SpecError):
            TPUJobSpec(name="bad", slice_type="v5e-8",
                       mesh=MeshSpec(data=3, pipeline=2))

    def test_tensor_alias_and_runtime_axes(self):
        """The CRD spells tensor-parallelism 'model'; the runtime
        (parallel/mesh.py) spells it 'tensor'.  Both vocabularies are
        accepted on input and runtime_axes() emits the runtime one, so
        an admitted spec.mesh can drive worker flags verbatim."""
        spec = MeshSpec.from_dict({"data": -1, "tensor": 4})
        assert spec.model == 4
        axes = spec.runtime_axes()
        assert axes["tensor"] == 4 and "model" not in axes
        with pytest.raises(SpecError, match="alias"):
            MeshSpec.from_dict({"model": 2, "tensor": 2})

    def test_camelcase_wire_schema(self):
        """The CR wire schema is uniformly camelCase; parse accepts it and
        rejects unknown fields with SpecError (admission error, not traceback)."""
        job = TPUJobSpec(name="j", slice_type="v5e-8",
                         worker=WorkerSpec(working_dir="/app"))
        cr = job.to_custom_resource()
        assert cr["spec"]["worker"]["workingDir"] == "/app"
        assert cr["spec"]["restartPolicy"]["maxRestarts"] == 3
        back = TPUJobSpec.from_custom_resource(cr)
        assert back.worker.working_dir == "/app"

    def test_unknown_worker_field_is_spec_error(self):
        cr = {"metadata": {"name": "x"},
              "spec": {"worker": {"image": "i", "wrokingDir": "/typo"}}}
        with pytest.raises(SpecError, match="unknown field"):
            TPUJobSpec.from_custom_resource(cr)

    def test_zero_mesh_axis_in_cr_is_spec_error(self):
        cr = {"metadata": {"name": "x"}, "spec": {"mesh": {"model": 0}}}
        with pytest.raises(SpecError):
            TPUJobSpec.from_custom_resource(cr)

    def test_tfjob_compat_replicas(self):
        """Reference-shaped TFJob replicaSpecs fold into the SPMD gang:
        PS dropped, WORKER template adopted (kubeflow/tf-job/tf-job.libsonnet:45-57)."""
        cr = {
            "apiVersion": "kubeflow-tpu.org/v1alpha1",
            "kind": "TPUJob",
            "metadata": {"name": "legacy", "namespace": "kubeflow"},
            "spec": {
                "sliceType": "v5e-8",
                "replicaSpecs": [
                    {"tfReplicaType": "PS", "replicas": 2,
                     "template": {"spec": {"containers": [
                         {"image": "ps:1"}]}}},
                    {"tfReplicaType": "WORKER", "replicas": 4,
                     "template": {"spec": {"containers": [
                         {"image": "worker:1",
                          "args": ["--train"]}]}}},
                ],
            },
        }
        job = TPUJobSpec.from_custom_resource(cr)
        assert job.worker.image == "worker:1"
        assert job.worker.args == ["--train"]
        # gang size comes from the slice, not the legacy replica counts
        assert job.num_workers == 1
