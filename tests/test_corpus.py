"""Real-text corpus tool: tokenizers, chunking, deterministic shards."""

import json

import numpy as np
import pytest

from kubeflow_tpu.tools import corpus


@pytest.fixture()
def tree(tmp_path):
    (tmp_path / "a.py").write_text("def add(a, b):\n    return a + b\n")
    (tmp_path / "b.md").write_text("# title\n\nSome prose here.\n" * 8)
    (tmp_path / "sub").mkdir()
    (tmp_path / "sub" / "c.txt").write_text("third document text\n" * 16)
    (tmp_path / "skip.bin").write_bytes(b"\x00\x01")
    return tmp_path


def test_byte_tokenizer_round_trips():
    tok = corpus.ByteTokenizer()
    text = "def f(x):\n    return x  # ünïcode\n"
    ids = tok.encode_ids(text)
    assert all(i >= 2 for i in ids)  # specials 0/1 never collide
    assert tok.decode(ids) == text


def test_iter_text_files_filters_and_caps(tree):
    files = corpus.iter_text_files([str(tree)])
    names = {f.name for f in files}
    assert names == {"a.py", "b.md", "c.txt"}
    capped = corpus.iter_text_files([str(tree)], max_bytes=40)
    assert 0 < len(capped) < 3
    # Same seed -> same selection (the A/B-shared-stream property).
    assert capped == corpus.iter_text_files([str(tree)], max_bytes=40)


def test_token_stream_chunks_with_eos_between_docs(tree):
    tok = corpus.ByteTokenizer()
    files = corpus.iter_text_files([str(tree)])
    chunks = list(corpus.token_stream(files, tok, seq_len=64))
    total_ids = sum(
        len(tok.encode_ids(f.read_text())) + 1 for f in files)
    assert len(chunks) == total_ids // 64  # partial tail dropped
    flat = np.concatenate(chunks)
    assert flat.dtype == np.int32
    assert (flat == corpus.EOS_ID).sum() >= len(files) - 1


def test_build_shards_and_train_stream(tree, tmp_path):
    tok = corpus.ByteTokenizer()
    files = corpus.iter_text_files([str(tree)])
    out = tmp_path / "shards"
    paths = corpus.build_shards(files, tok, 32, str(out),
                                examples_per_shard=4)
    assert paths
    from kubeflow_tpu.data.loader import RecordDataset, tensor_batches

    batch = next(iter(tensor_batches(RecordDataset(paths), 2)))
    assert batch["tokens"].shape == (2, 32)
    assert batch["tokens"].dtype == np.int32
    assert int(batch["tokens"].max()) < tok.vocab_size


def test_cli_end_to_end_bpe(tree, tmp_path, capsys):
    out = tmp_path / "corpus"
    rc = corpus.main([
        "--source", str(tree), "--tokenizer", "bpe",
        "--vocab-size", "300", "--seq-len", "16", "--out", str(out),
    ])
    assert rc == 0
    meta = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert meta["vocab_size"] <= 300
    assert (out / "tokenizer.json").exists()
    assert (out / "corpus.json").exists()
    tok = corpus.BpeTokenizer.load(str(out / "tokenizer.json"))
    ids = tok.encode_ids("def add(a, b):")
    assert ids and "def" in tok.decode(ids)
