"""LM serving path: export the flagship transformer, serve it, decode
over REST, and diff against a committed golden.

Round-2 gap (VERDICT #5): `loaders:lm_generate` was write-only code.
This is the golden-serving pattern the reference applied to its flagship
(Inception gRPC golden, testing/test_tf_serving.py +
components/k8s-model-server/images/test-worker/result.txt), applied to
THIS framework's flagship: the Transformer LM with KV-cache decode.

Regenerate after an intentional model change:
    KFT_UPDATE_GOLDEN=1 python -m pytest tests/test_lm_serving.py
"""

import json
import os
from pathlib import Path

import numpy as np
import pytest

GOLDEN = Path(__file__).parent / "golden" / "lm_generate.json"
SEED = 20260730
VOCAB, PROMPT_LEN, NEW_TOKENS = 128, 8, 12


@pytest.fixture(scope="module")
def served(tmp_path_factory):
    import jax

    from kubeflow_tpu.models.transformer import Transformer
    from kubeflow_tpu.serving.export import export
    from kubeflow_tpu.serving.http import ServingAPI
    from kubeflow_tpu.serving.loaders import _model_config
    from kubeflow_tpu.serving.model_server import ModelServer

    model_overrides = {
        "vocab_size": VOCAB, "d_model": 32, "n_layers": 2, "n_heads": 4,
        "n_kv_heads": 2, "d_ff": 64, "head_dim": 8, "max_seq_len": 64,
        "dtype": "float32",  # bit-stable across CPU/TPU for the golden
    }
    cfg = _model_config(model_overrides)
    model = Transformer(cfg)
    tokens = np.zeros((1, PROMPT_LEN), np.int32)
    variables = model.init(jax.random.key(SEED), tokens)
    base = tmp_path_factory.mktemp("models") / "lm"
    export(base, 1, variables,
           loader="kubeflow_tpu.serving.loaders:lm_generate",
           config={"model": model_overrides,
                   "max_new_tokens": NEW_TOKENS, "temperature": 0.0},
           signature={"inputs": ["tokens"], "outputs": ["tokens"]})
    server = ModelServer()
    server.add_model("lm", str(base))
    return ServingAPI(server)


def _prompt():
    rng = np.random.RandomState(SEED)
    return rng.randint(1, VOCAB, size=(PROMPT_LEN,)).tolist()


class TestLMServing:
    def test_decode_over_rest_matches_golden(self, served):
        out = served.predict("lm", {"instances": [{"tokens": _prompt()}]})
        tokens = out["predictions"][0]["tokens"]
        assert len(tokens) == PROMPT_LEN + NEW_TOKENS
        assert tokens[:PROMPT_LEN] == _prompt()  # prompt preserved
        got = {"tokens": tokens}
        if os.environ.get("KFT_UPDATE_GOLDEN"):
            GOLDEN.parent.mkdir(exist_ok=True)
            GOLDEN.write_text(json.dumps(got, indent=2) + "\n")
            pytest.skip("golden updated")
        assert GOLDEN.exists(), (
            "golden missing; regenerate with KFT_UPDATE_GOLDEN=1")
        want = json.loads(GOLDEN.read_text())
        assert got["tokens"] == want["tokens"], (
            "greedy decode drifted from the committed golden")

    def test_batched_decode(self, served):
        instances = [{"tokens": _prompt()}, {"tokens": _prompt()[::-1]}]
        out = served.predict("lm", {"instances": instances})
        assert len(out["predictions"]) == 2
        # Greedy decode is deterministic per row: identical prompts in a
        # batch produce identical continuations.
        same = served.predict(
            "lm", {"instances": [{"tokens": _prompt()}] * 2})
        rows = [p["tokens"] for p in same["predictions"]]
        assert rows[0] == rows[1]

    def test_metadata_reports_lm_loader(self, served):
        meta = served.metadata("lm")
        assert meta["metadata"]["loader"].endswith("lm_generate")
        assert meta["metadata"]["signature"]["inputs"] == ["tokens"]

def test_lm_logits_loader_serves_f32_regardless_of_ce_dtype(tmp_path):
    """ce_dtype='compute' changes the model forward's output dtype (a
    training-loss knob); the serving `lm` loader must still put float32
    logits on the wire."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from kubeflow_tpu.models.transformer import Transformer, TransformerConfig
    from kubeflow_tpu.serving.export import export
    from kubeflow_tpu.serving.model_server import ModelServer

    overrides = {
        "vocab_size": 64, "d_model": 16, "n_layers": 1, "n_heads": 2,
        "n_kv_heads": 2, "d_ff": 32, "head_dim": 8, "max_seq_len": 16,
        "dtype": "bfloat16", "ce_dtype": "compute",
    }
    cfg = TransformerConfig(**{**overrides, "dtype": jnp.bfloat16})
    model = Transformer(cfg)
    variables = model.init(jax.random.key(0), jnp.zeros((1, 4), jnp.int32))
    assert model.apply(variables, jnp.zeros((1, 4), jnp.int32)).dtype \
        == jnp.bfloat16  # the knob really does change the forward dtype
    export(str(tmp_path / "lm"), 1, variables,
           loader="kubeflow_tpu.serving.loaders:lm", config=overrides)
    server = ModelServer()
    server.add_model("lm", str(tmp_path / "lm"))
    out = server.predict("lm", {"tokens": np.asarray([[1, 2, 3]], np.int32)})
    assert np.asarray(out["logits"]).dtype == np.float32
