"""LM serving path: export the flagship transformer, serve it, decode
over REST, and diff against a committed golden.

Round-2 gap (VERDICT #5): `loaders:lm_generate` was write-only code.
This is the golden-serving pattern the reference applied to its flagship
(Inception gRPC golden, testing/test_tf_serving.py +
components/k8s-model-server/images/test-worker/result.txt), applied to
THIS framework's flagship: the Transformer LM with KV-cache decode.

Regenerate after an intentional model change:
    KFT_UPDATE_GOLDEN=1 python -m pytest tests/test_lm_serving.py
"""

import json
import os
from pathlib import Path

import numpy as np
import pytest

GOLDEN = Path(__file__).parent / "golden" / "lm_generate.json"
SEED = 20260730
VOCAB, PROMPT_LEN, NEW_TOKENS = 128, 8, 12


@pytest.fixture(scope="module")
def served(tmp_path_factory):
    import jax

    from kubeflow_tpu.models.transformer import Transformer
    from kubeflow_tpu.serving.export import export
    from kubeflow_tpu.serving.http import ServingAPI
    from kubeflow_tpu.serving.loaders import _model_config
    from kubeflow_tpu.serving.model_server import ModelServer

    model_overrides = {
        "vocab_size": VOCAB, "d_model": 32, "n_layers": 2, "n_heads": 4,
        "n_kv_heads": 2, "d_ff": 64, "head_dim": 8, "max_seq_len": 64,
        "dtype": "float32",  # bit-stable across CPU/TPU for the golden
    }
    cfg = _model_config(model_overrides)
    model = Transformer(cfg)
    tokens = np.zeros((1, PROMPT_LEN), np.int32)
    variables = model.init(jax.random.key(SEED), tokens)
    base = tmp_path_factory.mktemp("models") / "lm"
    export(base, 1, variables,
           loader="kubeflow_tpu.serving.loaders:lm_generate",
           config={"model": model_overrides,
                   "max_new_tokens": NEW_TOKENS, "temperature": 0.0},
           signature={"inputs": ["tokens"], "outputs": ["tokens"]})
    server = ModelServer()
    server.add_model("lm", str(base))
    return ServingAPI(server)


def _prompt():
    rng = np.random.RandomState(SEED)
    return rng.randint(1, VOCAB, size=(PROMPT_LEN,)).tolist()


class TestLMServing:
    def test_decode_over_rest_matches_golden(self, served):
        out = served.predict("lm", {"instances": [{"tokens": _prompt()}]})
        tokens = out["predictions"][0]["tokens"]
        assert len(tokens) == PROMPT_LEN + NEW_TOKENS
        assert tokens[:PROMPT_LEN] == _prompt()  # prompt preserved
        got = {"tokens": tokens}
        if os.environ.get("KFT_UPDATE_GOLDEN"):
            GOLDEN.parent.mkdir(exist_ok=True)
            GOLDEN.write_text(json.dumps(got, indent=2) + "\n")
            pytest.skip("golden updated")
        assert GOLDEN.exists(), (
            "golden missing; regenerate with KFT_UPDATE_GOLDEN=1")
        want = json.loads(GOLDEN.read_text())
        assert got["tokens"] == want["tokens"], (
            "greedy decode drifted from the committed golden")

    def test_batched_decode(self, served):
        instances = [{"tokens": _prompt()}, {"tokens": _prompt()[::-1]}]
        out = served.predict("lm", {"instances": instances})
        assert len(out["predictions"]) == 2
        # Greedy decode is deterministic per row: identical prompts in a
        # batch produce identical continuations.
        same = served.predict(
            "lm", {"instances": [{"tokens": _prompt()}] * 2})
        rows = [p["tokens"] for p in same["predictions"]]
        assert rows[0] == rows[1]

    def test_metadata_reports_lm_loader(self, served):
        meta = served.metadata("lm")
        assert meta["metadata"]["loader"].endswith("lm_generate")
        assert meta["metadata"]["signature"]["inputs"] == ["tokens"]

@pytest.fixture(scope="module")
def engine_model(tmp_path_factory):
    """A tiny exported lm_generate model served through ModelServer:
    yields (spec, server) where spec is the loader's engine_spec —
    config, HBM-staged params, decode settings — so the engine under
    test and the reference generate() run the IDENTICAL staged params."""
    import jax

    from kubeflow_tpu.models.transformer import Transformer
    from kubeflow_tpu.serving.export import export
    from kubeflow_tpu.serving.loaders import _model_config
    from kubeflow_tpu.serving.model_server import ModelServer

    overrides = {
        "vocab_size": VOCAB, "d_model": 32, "n_layers": 2, "n_heads": 4,
        "n_kv_heads": 2, "d_ff": 64, "head_dim": 8, "max_seq_len": 64,
        "dtype": "float32",
    }
    cfg = _model_config(overrides)
    model = Transformer(cfg)
    variables = model.init(
        jax.random.key(SEED), np.zeros((1, PROMPT_LEN), np.int32))
    base = tmp_path_factory.mktemp("engine-models") / "lm"
    export(base, 1, variables,
           loader="kubeflow_tpu.serving.loaders:lm_generate",
           config={"model": overrides,
                   "max_new_tokens": NEW_TOKENS, "temperature": 0.0})
    server = ModelServer()
    server.add_model("lm", str(base))
    yield server.get("lm").predict.engine_spec, server
    server.stop()


def _counting_proxy(fn, compiles, key):
    """Wrap a slot entry point so each .lower() call — exactly one XLA
    compilation in the engine, which AOT-compiles then only invokes
    the executables — bumps ``compiles[key]``.  Shared by the
    three-program and four-program compile-count tests so the two
    assertions can never silently diverge."""
    class _Proxy:
        def lower(self, *a, **kw):
            compiles[key] += 1
            return fn.lower(*a, **kw)

        def __call__(self, *a, **kw):
            return fn(*a, **kw)

    return _Proxy()


def _reference_rows(spec, prompts, news):
    """Single-request generate() goldens: per prompt, the greedy
    continuation truncated to that request's token budget (greedy is
    prefix-stable, so one full-budget run covers every shorter one)."""
    from kubeflow_tpu.models.generate import generate

    rows = []
    for prompt, new in zip(prompts, news):
        out, _ = generate(spec["cfg"], spec["params"],
                          np.asarray(prompt, np.int32)[None],
                          spec["decode"])
        rows.append(np.asarray(out)[0, :len(prompt) + new].tolist())
    return rows


class TestDecodeEngine:
    """Continuous-batching engine (serving/engine.py): generations must
    be token-identical to single-request generate(), across mixed
    prompt lengths, per-request budgets, and slot reuse — while
    compiling exactly two device programs for the whole workload
    (the third, speculative verify, only exists under
    ``speculative_tokens`` — see TestSpeculativeDecoding; prefix reuse
    is zero-copy block-table aliasing, never a device program)."""

    def test_matches_generate_mixed_lengths_slot_reuse_three_programs(
            self, engine_model, monkeypatch):
        import threading

        from kubeflow_tpu.models import generate as gen_mod
        from kubeflow_tpu.serving.engine import DecodeEngine

        compiles = {"chunked_prefill": 0, "step": 0, "verify": 0}
        for attr, key in (("prefill_chunk_into_slot", "chunked_prefill"),
                          ("decode_step", "step"),
                          ("verify_step", "verify")):
            monkeypatch.setattr(gen_mod, attr, _counting_proxy(
                getattr(gen_mod, attr), compiles, key))

        spec, _ = engine_model
        rng = np.random.RandomState(SEED)
        # 9 requests through 3 slots: every slot is reused at least
        # twice mid-run by later requests; lengths span 2..prefill_len
        # and budgets span 3..NEW_TOKENS.  (4 distinct lengths: each
        # distinct length costs one reference generate() compile.)
        # chunk width 8 < the longest prompts, so multi-chunk prefill
        # resumption is exercised; prefix caching is ON with a small
        # page so repeated short prefixes can alias.
        lens = [3, 9, 16, 2, 9, 16, 3, 16, 2]
        news = [12, 6, 3, 8, 12, 4, 10, 5, 12]
        prompts = [rng.randint(1, VOCAB, size=(n,)).tolist()
                   for n in lens]
        engine = DecodeEngine(spec["cfg"], spec["params"],
                              spec["decode"], slots=3, prefill_len=16,
                              admit_width=2, prefill_chunk_tokens=8,
                              kv_block_tokens=4, name="test-equiv")
        try:
            outs = [None] * len(prompts)

            def client(i):
                outs[i] = engine.submit({
                    "tokens": np.asarray(prompts[i], np.int32),
                    "max_new_tokens": news[i]})

            threads = [threading.Thread(target=client, args=(i,))
                       for i in range(len(prompts))]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            want = _reference_rows(spec, prompts, news)
            for i, out in enumerate(outs):
                got = np.asarray(out["tokens"])[0].tolist()
                assert got == want[i], (
                    f"request {i} (len {lens[i]}, budget {news[i]}) "
                    "drifted from single-request generate()")
            stats = engine.stats()
            assert stats["requests"] == len(prompts)
            assert stats["active_slots"] == 0
            assert stats["queue_depth"] == 0
            assert stats["in_flight_requests"] == 0
            assert stats["tokens"] == sum(news)
        finally:
            engine.close()
        # The whole mixed workload — admission waves, slot reuse,
        # varying budgets, multi-chunk prefills, zero-copy prefix
        # aliasing — compiled exactly two programs (no speculative
        # verify: this engine runs with speculation off; no prefix
        # copy program EXISTS — a cache hit is a block-table edit).
        two = {"chunked_prefill": 1, "step": 1, "verify": 0}
        assert compiles == two
        assert engine.compiled_programs() == two

    def test_eos_retirement_matches_generate(self, engine_model):
        """With EOS configured, a slot frozen by the device `done` flag
        must emit exactly generate()'s tokens up to and including EOS,
        and its slot must come back (occupancy drains to zero)."""
        import dataclasses

        from kubeflow_tpu.models.generate import generate
        from kubeflow_tpu.serving.engine import DecodeEngine

        spec, _ = engine_model
        rng = np.random.RandomState(SEED + 1)
        decode = dataclasses.replace(spec["decode"], eos_token=5)
        prompts = [rng.randint(1, VOCAB, size=(n,)).tolist()
                   for n in (3, 9, 16)]
        engine = DecodeEngine(spec["cfg"], spec["params"], decode,
                              slots=2, prefill_len=16, name="test-eos")
        try:
            for prompt in prompts:
                out = engine.submit(
                    {"tokens": np.asarray(prompt, np.int32)})
                got = np.asarray(out["tokens"])[0, len(prompt):].tolist()
                ref, _ = generate(spec["cfg"], spec["params"],
                                  np.asarray(prompt, np.int32)[None],
                                  decode)
                ref = np.asarray(ref)[0, len(prompt):].tolist()
                if 5 in ref:
                    ref = ref[:ref.index(5) + 1]
                assert got == ref
            assert engine.stats()["active_slots"] == 0
        finally:
            engine.close()

    def test_abort_resolves_retired_requests(self, engine_model,
                                             monkeypatch):
        """Engine death must error EVERY waiter — including a request
        whose slot was deterministically retired at dispatch while its
        lagged emission still sat in the pending stream (it is in
        neither the queue nor the slot table when _abort walks them)."""
        import threading

        from kubeflow_tpu.models import generate as gen_mod
        from kubeflow_tpu.serving.engine import DecodeEngine

        real = gen_mod.decode_step
        calls = {"n": 0}

        class _DiesOnSecondStep:
            def lower(self, *a, **kw):
                lowered = real.lower(*a, **kw)

                class _Lowered:
                    def compile(self_l):
                        exe = lowered.compile()

                        def run(*ra, **rkw):
                            calls["n"] += 1
                            if calls["n"] >= 2:
                                raise RuntimeError("device died")
                            return exe(*ra, **rkw)

                        return run

                return _Lowered()

        monkeypatch.setattr(gen_mod, "decode_step", _DiesOnSecondStep())
        spec, _ = engine_model
        # sync_lag larger than the steps the workload survives: the
        # short request's tokens are never drained before the blow-up.
        engine = DecodeEngine(spec["cfg"], spec["params"],
                              spec["decode"], slots=2, prefill_len=16,
                              sync_lag=4, name="test-abort")
        outs: dict = {}

        def client(i, new):
            try:
                outs[i] = engine.submit({
                    "tokens": np.arange(1, 5, dtype=np.int32),
                    "max_new_tokens": new})
            except Exception as exc:  # noqa: BLE001 — the point
                outs[i] = exc

        threads = [threading.Thread(target=client, args=a)
                   for a in ((0, 2), (1, 12))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert not any(t.is_alive() for t in threads), (
            "a client hung after the engine loop died")
        assert len(outs) == 2  # every waiter resolved (result or error)
        engine.close()

    def test_prefix_cache_identity_on_off_with_eviction(
            self, engine_model):
        """Shared-prefix aliasing must be invisible in the tokens:
        engine output with the prefix cache ON equals single-request
        generate() equals cache OFF — including LRU eviction forced
        MID-STREAM (a deliberately tight block pool contended by two
        prefix families over 2 slots) and slot reuse after retirement
        (8 requests through 2 slots).  The paged pool must drain
        COMPLETELY on close: no block leaks, no dangling refcounts."""
        import threading

        from kubeflow_tpu.serving.engine import DecodeEngine

        spec, _ = engine_model
        rng = np.random.RandomState(SEED + 7)
        prefix_a = rng.randint(1, VOCAB, size=(8,)).tolist()
        prefix_b = rng.randint(1, VOCAB, size=(8,)).tolist()
        prompts = []
        for fam in (prefix_a, prefix_a, prefix_b, prefix_a,
                    prefix_b, prefix_a, prefix_b, prefix_a):
            prompts.append(
                fam + rng.randint(1, VOCAB, size=(5,)).tolist())
        news = [6, 9, 5, 12, 8, 4, 10, 7]
        want = _reference_rows(spec, prompts, news)

        def run(caching):
            # 10 pages of 4 tokens: a 13-token prompt + 12-budget
            # worst case reserves 7, so two co-resident requests
            # exceed the pool unless retired pages recycle — cached
            # records get LRU-evicted under allocation pressure while
            # later same-family requests still hit.
            engine = DecodeEngine(
                spec["cfg"], spec["params"], spec["decode"], slots=2,
                prefill_len=16, prefill_chunk_tokens=4,
                kv_block_tokens=4, kv_pool_blocks=10,
                prefix_caching=caching,
                name=f"test-prefix-{int(caching)}")
            try:
                outs = [None] * len(prompts)

                def client(i):
                    outs[i] = engine.submit({
                        "tokens": np.asarray(prompts[i], np.int32),
                        "max_new_tokens": news[i]})

                threads = [threading.Thread(target=client, args=(i,))
                           for i in range(len(prompts))]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join()
                engine._mgr.check_invariants()
                return outs, engine.stats(), engine
            finally:
                engine.close()

        on_outs, on_stats, on_engine = run(caching=True)
        off_outs, off_stats, off_engine = run(caching=False)
        for i in range(len(prompts)):
            got_on = np.asarray(on_outs[i]["tokens"])[0].tolist()
            got_off = np.asarray(off_outs[i]["tokens"])[0].tolist()
            assert got_on == want[i], f"cache ON drifted on request {i}"
            assert got_off == want[i], f"cache OFF drifted on request {i}"
        # The pool really was contended: both families admitted, so
        # cached pages were reclaimed (record + block eviction
        # counters moved), and at least one later same-family request
        # still hit.
        assert on_stats["prefix_hits"] >= 1
        assert on_stats["prefix_evictions"] >= 1
        assert on_stats["kv_block_evictions"] >= 1
        assert on_stats["cached_prompt_tokens"] >= 8
        assert 0 < on_stats["cached_token_ratio"] < 1
        assert off_stats["prefix_hits"] == 0
        assert off_stats["cached_token_ratio"] == 0.0
        assert off_stats["kv_blocks_used"] == 0  # nothing cached
        # Everything returned to both pools after close().
        assert on_engine._mgr.used_blocks() == 0
        assert off_engine._mgr.used_blocks() == 0

    def test_shared_prefix_zero_copy_aliasing_identity(
            self, engine_model):
        """Two requests sharing a block-aligned prefix must produce
        bit-identical tokens to unshared runs while the engine copies
        ZERO prefix tokens: the hit is a refcounted block-table alias
        of the pages the first prefill wrote — the sharer's table
        leads with the SAME physical block ids the published record
        advertises, and no copy program exists to run."""
        from kubeflow_tpu.serving.engine import DecodeEngine

        spec, _ = engine_model
        rng = np.random.RandomState(SEED + 13)
        common = rng.randint(1, VOCAB, size=(8,)).tolist()
        p1 = common + rng.randint(1, VOCAB, size=(4,)).tolist()
        p2 = common + rng.randint(1, VOCAB, size=(6,)).tolist()
        want = _reference_rows(spec, [p1, p2], [6, 6])
        engine = DecodeEngine(
            spec["cfg"], spec["params"], spec["decode"], slots=2,
            prefill_len=16, prefill_chunk_tokens=8, kv_block_tokens=4,
            name="test-zero-copy")
        try:
            o1 = engine.submit({"tokens": np.asarray(p1, np.int32),
                                "max_new_tokens": 6})
            # The published record's physical pages (the prefix's k/v,
            # written once by p1's prefill).
            with engine._lock:
                recs = list(engine._mgr._lru.values())
            assert recs, "p1's prefill published no prefix record"
            published = list(recs[0].blocks)
            o2 = engine.submit({"tokens": np.asarray(p2, np.int32),
                                "max_new_tokens": 6,
                                "return_timing": True})
            assert np.asarray(o1["tokens"])[0].tolist() == want[0]
            assert np.asarray(o2["tokens"])[0].tolist() == want[1], (
                "shared-prefix resume drifted from the unshared run")
            stats = engine.stats()
            # The full 8-token (2-page) prefix was served by aliasing:
            # cached tokens counted, zero device copies possible —
            # there is no copy program in the compiled set at all.
            assert o2["cached_tokens"] == 8
            assert stats["prefix_hits"] == 1
            assert stats["cached_prompt_tokens"] == 8
            assert set(stats["compiled_programs"]) == {
                "chunked_prefill", "step", "verify"}
            # White-box: the alias really is the SAME physical pages —
            # p2's own published record leads with p1's block ids (its
            # prefill never wrote new pages for the shared prefix; a
            # copy would have needed fresh ones).
            with engine._lock:
                recs = list(engine._mgr._lru.values())
            assert any(r.blocks[:2] == published[:2]
                       and len(r.blocks) > 2 for r in recs), (
                "sharer's record does not alias the donor's pages")
            engine._mgr.check_invariants()
        finally:
            engine.close()
        assert engine._mgr.used_blocks() == 0

    def test_int8_kv_rides_the_paged_pool(self, engine_model):
        """The unified KV store is ONE block pool for fp and int8
        QTensor caches alike: with kv_cache_dtype='int8' the engine
        must stay token-identical to int8 generate() — including a
        zero-copy prefix hit, whose aliased pages hold k/v the donor
        quantized (same tokens at same positions quantize identically,
        so aliasing is exact)."""
        import dataclasses

        from kubeflow_tpu.models.generate import generate
        from kubeflow_tpu.serving.engine import DecodeEngine

        spec, _ = engine_model
        decode = dataclasses.replace(spec["decode"],
                                     kv_cache_dtype="int8")
        rng = np.random.RandomState(SEED + 31)
        common = rng.randint(1, VOCAB, size=(8,)).tolist()
        prompts = [common + rng.randint(1, VOCAB, size=(n,)).tolist()
                   for n in (4, 6)] \
            + [rng.randint(1, VOCAB, size=(9,)).tolist()]
        engine = DecodeEngine(
            spec["cfg"], spec["params"], decode, slots=2,
            prefill_len=16, prefill_chunk_tokens=8, kv_block_tokens=4,
            name="test-int8-paged")
        try:
            for p in prompts:
                out = engine.submit({"tokens": np.asarray(p, np.int32)})
                ref, _ = generate(spec["cfg"], spec["params"],
                                  np.asarray(p, np.int32)[None], decode)
                assert np.asarray(out["tokens"])[0].tolist() \
                    == np.asarray(ref)[0].tolist(), (
                    "int8 paged engine drifted from int8 generate()")
            assert engine.stats()["prefix_hits"] == 1
            engine._mgr.check_invariants()
        finally:
            engine.close()
        assert engine._mgr.used_blocks() == 0

    def test_pool_exhaustion_sheds_typed_overloaded(self, engine_model):
        """A request whose worst-case page count can never fit the
        pool sheds typed Overloaded AT SUBMIT (429, kv-attributed in
        stats) instead of queueing forever; a fitting request on the
        same engine still serves (admission reserves worst case, so a
        mid-flight slot can never deadlock on pages)."""
        from kubeflow_tpu.serving.engine import DecodeEngine
        from kubeflow_tpu.serving.errors import Overloaded

        spec, _ = engine_model
        engine = DecodeEngine(
            spec["cfg"], spec["params"], spec["decode"], slots=2,
            prefill_len=16, kv_block_tokens=4, kv_pool_blocks=3,
            name="test-exhaust")
        try:
            # 12 prompt + 12 budget = 6 pages > the 3-page pool.
            with pytest.raises(Overloaded):
                engine.submit({
                    "tokens": np.arange(1, 13, dtype=np.int32)})
            stats = engine.stats()
            assert stats["shed"] == 1
            assert stats["kv_shed_no_blocks"] == 1
            assert stats["kv_blocks"] == 3
            # 2 prompt + 4 budget = 2 pages: fits, serves.
            out = engine.submit({
                "tokens": np.asarray([3, 4], np.int32),
                "max_new_tokens": 4})
            assert np.asarray(out["tokens"]).shape == (1, 6)
            stats = engine.stats()
            assert stats["requests"] == 1
            assert stats["tokens_resident"] \
                == stats["kv_blocks_used"] * 4
            assert 0 <= stats["kv_utilization"] <= 1
        finally:
            engine.close()

    def test_prefix_cache_invalidated_on_model_reload(self,
                                                      engine_model):
        """The prefix index must die with the model version: rebuilding
        the batching plane (what ModelServer does around every
        hot-swapped version) yields an engine with an EMPTY cache —
        no stale-prefix KV can leak across versions — and identical
        tokens before and after."""
        from kubeflow_tpu.serving.main import batcher_factory

        spec, server = engine_model
        factory = batcher_factory(
            micro_batch_size=0, batch_timeout_s=0.005, lm_engine=True,
            lm_engine_slots=2, lm_engine_prefill_len=16,
            prefill_chunk_tokens=8, kv_block_tokens=4)
        prompt = _prompt()
        want = _reference_rows(spec, [prompt], [NEW_TOKENS])[0]
        try:
            server.enable_batching("lm", factory)
            for _ in range(2):  # second submit hits the cached prefix
                out = server.predict(
                    "lm", {"tokens": np.asarray(prompt, np.int32)[None]})
                assert np.asarray(out["tokens"])[0].tolist() == want
            stats = server.batcher_stats("lm")
            assert stats["prefix_hits"] >= 1
            # Rebuild = the reload path's batcher swap: fresh engine,
            # fresh pool, fresh index.
            server.enable_batching("lm", factory)
            stats = server.batcher_stats("lm")
            assert stats["prefix_hits"] == 0
            assert stats["cached_prompt_tokens"] == 0
            out = server.predict(
                "lm", {"tokens": np.asarray(prompt, np.int32)[None]})
            assert np.asarray(out["tokens"])[0].tolist() == want
            stats = server.batcher_stats("lm")
            assert stats["prefix_hits"] == 0  # cold cache: a miss
            assert stats["prefix_misses"] >= 1
        finally:
            server.enable_batching("lm", lambda model: None)

    def test_padded_prompt_counts_true_tokens(self, engine_model):
        """accepts()/submit() must validate the REAL token count, not
        the padded width: a 5-token prompt right-padded to 24 (beyond
        the 16-wide prefill window) is admitted, prefilled at its true
        length (no pad ids in its context), and generates exactly what
        generate() produces for the unpadded prompt."""
        from kubeflow_tpu.serving.engine import DecodeEngine

        spec, _ = engine_model
        rng = np.random.RandomState(SEED + 9)
        real = rng.randint(1, VOCAB, size=(5,)).tolist()
        padded = np.zeros((24,), np.int32)
        padded[:5] = real
        want = _reference_rows(spec, [real], [6])[0]
        engine = DecodeEngine(spec["cfg"], spec["params"],
                              spec["decode"], slots=1, prefill_len=16,
                              name="test-padded")
        try:
            assert engine.accepts({"tokens": padded})
            out = engine.submit({"tokens": padded, "max_new_tokens": 6})
            assert np.asarray(out["tokens"])[0].tolist() == want
            # Explicit prompt_len wins over the trailing-pad heuristic
            # (a prompt whose real tail IS token 0 stays intact).
            assert engine.accepts(
                {"tokens": padded, "prompt_len": np.int32(5)})
            out = engine.submit({"tokens": padded, "prompt_len": 5,
                                 "max_new_tokens": 6})
            assert np.asarray(out["tokens"])[0].tolist() == want
            # A prompt whose REAL length exceeds the window still falls
            # back (accepts() False), padded or not.
            wide = np.arange(1, 25, dtype=np.int32)
            assert not engine.accepts({"tokens": wide})
        finally:
            engine.close()

    def test_final_chunk_near_cache_end_stays_in_bounds(
            self, engine_model):
        """A cached-prefix resume whose final chunk window runs past
        the slot's max_len must not corrupt the cache: the paged
        scatter parks positions beyond the block table's real pages on
        the sentinel and DROPS them (they sit beyond every frontier
        the slot can reach), so overhang costs nothing — unlike the
        old contiguous layout, where XLA's dynamic_update_slice would
        CLAMP the out-of-bounds start and shift the chunk onto earlier
        valid columns.  Geometry: prefill_len=16, max_len=18, chunk 8,
        a 12-column cached prefix -> naive window [12, 20) > 18."""
        from kubeflow_tpu.serving.engine import DecodeEngine

        spec, _ = engine_model
        rng = np.random.RandomState(SEED + 11)
        prompt = rng.randint(1, VOCAB, size=(15,)).tolist()
        want = _reference_rows(spec, [prompt, prompt], [3, 3])
        engine = DecodeEngine(
            spec["cfg"], spec["params"], spec["decode"], slots=1,
            prefill_len=16, max_len=18, prefill_chunk_tokens=8,
            kv_block_tokens=4, name="test-chunk-bounds")
        try:
            for i in range(2):  # second run resumes from 12 cached cols
                out = engine.submit({
                    "tokens": np.asarray(prompt, np.int32),
                    "max_new_tokens": 3})
                assert np.asarray(out["tokens"])[0].tolist() == want[i]
            stats = engine.stats()
            assert stats["prefix_hits"] == 1
            assert stats["cached_prompt_tokens"] == 12
        finally:
            engine.close()

    def test_budget_clamped_to_config(self, engine_model):
        """A request asking for more than the export config's
        max_new_tokens gets the config budget — the model's advertised
        ceiling, same as the direct path's trim — not the engine's
        whole cache headroom."""
        from kubeflow_tpu.serving.engine import DecodeEngine

        spec, _ = engine_model
        engine = DecodeEngine(spec["cfg"], spec["params"],
                              spec["decode"], slots=1, prefill_len=16,
                              name="test-clamp")
        try:
            out = engine.submit({
                "tokens": np.arange(1, 4, dtype=np.int32),
                "max_new_tokens": 500})
            assert np.asarray(out["tokens"]).shape == (1, 3 + NEW_TOKENS)
        finally:
            engine.close()

    def test_deterministic_shutdown(self, engine_model):
        """close() refuses new work, drains in-flight requests, and
        joins the loop thread within its bounded deadline — no
        background-thread leakage across the pytest session."""
        from kubeflow_tpu.serving.engine import DecodeEngine
        from kubeflow_tpu.serving.model_server import BatcherClosed

        spec, _ = engine_model
        engine = DecodeEngine(spec["cfg"], spec["params"],
                              spec["decode"], slots=2, prefill_len=16,
                              name="test-shutdown")
        out = engine.submit({"tokens": np.arange(1, 6, dtype=np.int32),
                             "max_new_tokens": 4})
        assert np.asarray(out["tokens"]).shape == (1, 9)
        engine.close(drain_s=5.0)
        assert not engine._thread.is_alive()
        with pytest.raises(BatcherClosed):
            engine.submit({"tokens": np.arange(1, 6, dtype=np.int32)})
        engine.close()  # idempotent

    def test_factory_declines_engine_without_prompt_room(self):
        """An export whose completion budget consumes the whole context
        (max_new_tokens >= max_seq_len) must fall back to the static
        paths, not crash serving startup (or a watcher reload) with an
        engine construction error."""
        from types import SimpleNamespace

        from kubeflow_tpu.serving.main import batcher_factory

        def predict(inputs):
            return inputs

        predict.engine_spec = {
            "cfg": SimpleNamespace(max_seq_len=64),
            "decode": SimpleNamespace(max_new_tokens=64),
            "params": None,
        }
        model = SimpleNamespace(name="lm", version=1, predict=predict)
        factory = batcher_factory(micro_batch_size=0,
                                  batch_timeout_s=0.01)
        assert factory(model) is None  # direct path, no crash

    def test_rest_routing_and_stats_route(self, engine_model):
        """Wired behind ModelServer via the serving entrypoint's
        factory, the engine serves the REST predict path (token-
        identical to the direct path) and the :stats route exposes its
        locked snapshot."""
        from kubeflow_tpu.serving.http import ServingAPI
        from kubeflow_tpu.serving.main import batcher_factory

        spec, server = engine_model
        server.enable_batching("lm", batcher_factory(
            micro_batch_size=0, batch_timeout_s=0.005,
            lm_engine=True, lm_engine_slots=2,
            lm_engine_prefill_len=16))
        try:
            api = ServingAPI(server)
            out = api.predict(
                "lm", {"instances": [{"tokens": _prompt()}]})
            tokens = out["predictions"][0]["tokens"]
            want = _reference_rows(spec, [_prompt()], [NEW_TOKENS])[0]
            assert tokens == want
            stats = api.stats("lm")["batcher"]
            assert stats["requests"] >= 1
            assert stats["slots"] == 2
            assert stats["active_slots"] == 0
            # A prompt wider than the engine's static prefill width
            # falls back to the direct generate() path (accepts()).
            wide = list(range(1, 33))
            out = api.predict("lm", {"instances": [{"tokens": wide}]})
            assert len(out["predictions"][0]["tokens"]) \
                == len(wide) + NEW_TOKENS
        finally:
            server.enable_batching("lm", lambda model: None)

    @pytest.mark.slow
    def test_throughput_beats_static_batcher(self):
        """Mixed-length open-loop workload: the continuous engine's
        delivered tokens/sec must beat the static BucketedLMBatcher.

        Drives bench.py's lm_engine section directly — same request
        set, same arrival schedule on both sides, stall-resistant
        interleaved windows with max-window capability estimates — so
        this test and the recorded BENCH number are one measurement.
        (A smaller hand-rolled version of this comparison flaked: on
        the CPU smoke model the engine's host-loop overhead and the
        box's scheduling noise are the same order as the structural
        win, and only the bench's windowing rides that out.)"""
        import bench

        import jax

        devices = jax.devices()
        record = bench.bench_lm_engine(None, devices, len(devices),
                                       on_tpu=False)
        detail = record["detail"]
        assert detail["compiled_programs"] == {
            "chunked_prefill": 1, "step": 1, "verify": 0}
        assert detail["engine_vs_batcher"] > 1.0, (
            f"engine {detail['engine_tokens_per_sec']} tok/s did not "
            f"beat static batcher {detail['batcher_tokens_per_sec']} "
            "tok/s on the bench's mixed-length open-loop workload")


class TestSpeculativeDecoding:
    """Token-identity battery for self-speculative decoding
    (serving/engine.py speculative_tokens + models/generate.py
    verify_step): speculation must be INVISIBLE in the tokens — spec ON
    == spec OFF == single-request generate() on every path, including
    forced full rejection, mid-stream EOS inside an accepted draft
    window, and device-side rollback followed by slot reuse."""

    def _mixed_workload(self):
        """Prompts the drafter can and cannot predict: pattern-tiled
        (repetitive — greedy continuations of the tiny model collapse
        into cycles the n-gram drafter proposes) interleaved with
        random ones (the drafter finds no suffix match early on, so
        plain decode rounds run too — both the step AND verify
        programs must compile)."""
        rng = np.random.RandomState(SEED + 21)
        prompts, news = [], []
        for i in range(8):
            if i % 2 == 0:
                pat = rng.randint(1, VOCAB, size=(4,))
                prompts.append(np.tile(pat, 3).tolist())
            else:
                prompts.append(
                    rng.randint(1, VOCAB, size=(10,)).tolist())
            news.append([12, 8, 10, 6][i % 4])
        return prompts, news

    def _run_engine(self, spec, prompts, news, *, speculative_tokens,
                    slots=2, decode=None, name="test-spec"):
        import threading

        from kubeflow_tpu.serving.engine import DecodeEngine

        engine = DecodeEngine(
            spec["cfg"], spec["params"], decode or spec["decode"],
            slots=slots, prefill_len=16, prefill_chunk_tokens=8,
            kv_block_tokens=4,
            speculative_tokens=speculative_tokens,
            name=f"{name}-{speculative_tokens}")
        try:
            outs = [None] * len(prompts)

            def client(i):
                outs[i] = engine.submit({
                    "tokens": np.asarray(prompts[i], np.int32),
                    "max_new_tokens": news[i]})

            threads = [threading.Thread(target=client, args=(i,))
                       for i in range(len(prompts))]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            return outs, engine.stats()
        finally:
            engine.close()

    def test_spec_on_equals_spec_off_equals_generate_three_programs(
            self, engine_model, monkeypatch):
        """The tentpole identity: a mixed repetitive/random workload
        with slot reuse is token-identical across spec ON, spec OFF,
        and generate(), real draft acceptance happened, and the spec-ON
        engine compiled exactly the three programs."""
        import kubeflow_tpu.serving.engine as eng_mod

        from kubeflow_tpu.models import generate as gen_mod

        # The measured-throughput gate is timing-based (delivered-rate
        # EMAs of real device calls) — on a loaded CI box it can
        # legitimately veto verify rounds and starve the acceptance
        # counters this test asserts on.  Zero the margin so every
        # proposed round verifies: identity is what is under test
        # here, and it must hold regardless of gating.
        monkeypatch.setattr(eng_mod, "_SPEC_RATE_MARGIN", 0.0)

        compiles = {"chunked_prefill": 0, "step": 0, "verify": 0}
        for attr, key in (("prefill_chunk_into_slot", "chunked_prefill"),
                          ("decode_step", "step"),
                          ("verify_step", "verify")):
            monkeypatch.setattr(gen_mod, attr, _counting_proxy(
                getattr(gen_mod, attr), compiles, key))

        spec, _ = engine_model
        prompts, news = self._mixed_workload()
        want = _reference_rows(spec, prompts, news)
        on_outs, on_stats = self._run_engine(
            spec, prompts, news, speculative_tokens=4)
        off_outs, off_stats = self._run_engine(
            spec, prompts, news, speculative_tokens=0)
        for i in range(len(prompts)):
            got_on = np.asarray(on_outs[i]["tokens"])[0].tolist()
            got_off = np.asarray(off_outs[i]["tokens"])[0].tolist()
            assert got_on == want[i], f"spec ON drifted on request {i}"
            assert got_off == want[i], f"spec OFF drifted on request {i}"
        # Speculation really ran: drafts proposed, some accepted, and
        # the counters reconcile (accepted <= drafted, both visible in
        # the acceptance-rate stats).
        assert on_stats["spec_drafted"] > 0
        assert 0 < on_stats["spec_accepted"] <= on_stats["spec_drafted"]
        assert 0 < on_stats["spec_acceptance_rate"] <= 1
        assert on_stats["accepted_per_step"] > 0
        assert on_stats["spec_steps"] > 0
        assert off_stats["spec_drafted"] == 0
        assert off_stats["spec_steps"] == 0
        # Three programs, each compiled once across BOTH engines (the
        # spec-OFF engine reuses two of the same .lower sites and
        # never lowers verify).
        assert compiles == {"chunked_prefill": 2, "step": 2,
                            "verify": 1}
        assert on_stats["compiled_programs"] == {
            "chunked_prefill": 1, "step": 1, "verify": 1}
        assert off_stats["compiled_programs"]["verify"] == 0

    def test_forced_full_rejection_rollback_and_slot_reuse(
            self, engine_model, monkeypatch):
        """An always-wrong drafter forces every draft to reject: the
        device-side rollback (cache_len reset over the rejected
        columns) must leave the slot's cache exactly as sequential
        decode would have, across REPEATED requests through one slot —
        no stale rejected-draft column may ever leak into a later
        request's attention."""
        import kubeflow_tpu.serving.engine as eng_mod

        spec, _ = engine_model
        rng = np.random.RandomState(SEED + 23)
        pat = rng.randint(1, VOCAB, size=(4,))
        prompts = [np.tile(pat, 3).tolist(),
                   rng.randint(1, VOCAB, size=(9,)).tolist(),
                   np.tile(pat, 3).tolist()]
        news = [12, 10, 12]
        want = _reference_rows(spec, prompts, news)

        def always_wrong(history, k, *a, **kw):
            # Guaranteed full rejection BY CONSTRUCTION: propose the
            # reference continuation shifted by one in vocab space —
            # the greedy target IS the reference token at each
            # position, and (t + 1) % VOCAB != t always.  (Shifting
            # the real drafter's proposal instead would not guarantee
            # a mismatch: a proposal already one below the target
            # would shift ONTO it.)
            hist = history.tolist()
            for prompt, ref in zip(prompts, want):
                if len(hist) >= len(prompt) \
                        and hist[:len(prompt)] == prompt:
                    emitted = len(hist) - len(prompt)
                    nxt = ref[len(prompt) + emitted:
                              len(prompt) + emitted + k]
                    return ((np.asarray(nxt, np.int64) + 1)
                            % VOCAB).astype(np.int32)
            return np.empty((0,), np.int32)  # unknown prompt: no draft

        monkeypatch.setattr(eng_mod, "_ngram_propose", always_wrong)
        outs, stats = self._run_engine(
            spec, prompts, news, speculative_tokens=4, slots=1,
            name="test-reject")
        for i in range(len(prompts)):
            got = np.asarray(outs[i]["tokens"])[0].tolist()
            assert got == want[i], (
                f"request {i} drifted after full-rejection rollback")
        assert stats["spec_drafted"] > 0
        assert stats["spec_accepted"] == 0
        assert stats["spec_acceptance_rate"] == 0.0
        assert stats["active_slots"] == 0

    def test_eos_inside_accepted_draft_window(self, engine_model,
                                              monkeypatch):
        """EOS emitted MID-WINDOW: an oracle drafter (proposes the true
        greedy continuation) guarantees the draft window is fully
        accepted, so the EOS lands inside it — the device must cut the
        emission at EOS, freeze the slot, and the next request must
        reuse it cleanly."""
        import dataclasses

        import kubeflow_tpu.serving.engine as eng_mod

        from kubeflow_tpu.models.generate import generate

        spec, _ = engine_model
        rng = np.random.RandomState(SEED + 25)
        # Pick a prompt whose greedy continuation contains a token
        # FIRST appearing at index >= 2: configured as EOS, a fully
        # accepted 4-token draft window emits it mid-window, never as
        # the window's first token.  (Tiny random-init models collapse
        # to constant runs fast, so search a few candidate prompts.)
        prompt = cont = eos = eos_idx = None
        for _ in range(16):
            cand = rng.randint(1, VOCAB, size=(10,)).tolist()
            ref, _ = generate(spec["cfg"], spec["params"],
                              np.asarray(cand, np.int32)[None],
                              spec["decode"])
            cand_cont = np.asarray(ref)[0, len(cand):].tolist()
            for idx in range(2, len(cand_cont)):
                if cand_cont[idx] not in cand_cont[:idx]:
                    prompt, cont = cand, cand_cont
                    eos, eos_idx = cand_cont[idx], idx
                    break
            if eos is not None:
                break
        assert eos is not None, (
            "no candidate prompt produced a usable mid-stream EOS "
            "token; widen the search")
        decode = dataclasses.replace(spec["decode"], eos_token=eos)
        want = cont[:eos_idx + 1]

        def oracle(history, k, *a, **kw):
            emitted = len(history) - len(prompt)
            nxt = cont[emitted:emitted + k]
            return np.asarray(nxt, np.int32)

        monkeypatch.setattr(eng_mod, "_ngram_propose", oracle)
        outs, stats = self._run_engine(
            spec, [prompt, prompt], [NEW_TOKENS, NEW_TOKENS],
            speculative_tokens=4, slots=1, decode=decode,
            name="test-eos-window")
        for i in range(2):  # second request = slot reuse after EOS
            got = np.asarray(outs[i]["tokens"])[0, len(prompt):].tolist()
            assert got == want, (
                f"request {i}: EOS-in-window emission {got} != {want}")
        # The window really was speculative: drafts were accepted
        # before (and including) the EOS cut.
        assert stats["spec_accepted"] > 0

    def test_sampling_export_disables_speculation(self, engine_model):
        """Speculation is greedy-only: a sampling export silently falls
        back to plain decode (verify would accept argmax tokens the
        sampler never drew), and the engine still serves."""
        import dataclasses

        spec, _ = engine_model
        decode = dataclasses.replace(spec["decode"], temperature=0.7)
        outs, stats = self._run_engine(
            spec, [[1, 2, 3, 4]], [6], speculative_tokens=4,
            slots=1, decode=decode, name="test-sampling")
        assert np.asarray(outs[0]["tokens"]).shape == (1, 10)
        assert stats["spec_steps"] == 0
        assert stats["spec_drafted"] == 0
        assert stats["compiled_programs"]["verify"] == 0

    def test_ngram_propose_unit(self):
        """The drafter itself: repeated suffixes propose their
        historical continuation; unrepetitive histories propose
        nothing (the engine then runs plain decode)."""
        from kubeflow_tpu.serving.engine import _ngram_propose

        hist = np.asarray([5, 9, 7, 3, 9, 7], np.int32)
        # Suffix [9, 7] recurred at positions 1-2 -> propose what
        # followed it: [3, 9, 7], truncated to k.
        assert _ngram_propose(hist, 3).tolist() == [3, 9, 7]
        assert _ngram_propose(hist, 1).tolist() == [3]
        # No repeated suffix at all -> empty proposal.
        assert _ngram_propose(
            np.asarray([1, 2, 3, 4, 5], np.int32), 4).size == 0
        # Degenerate histories never crash the drafter.
        assert _ngram_propose(np.asarray([7], np.int32), 4).size == 0
        # Constant run: suffix matches one step back, proposal
        # continues the run.
        run = np.full((6,), 8, np.int32)
        assert _ngram_propose(run, 2).tolist() == [8, 8]


def test_lm_logits_loader_serves_f32_regardless_of_ce_dtype(tmp_path):
    """ce_dtype='compute' changes the model forward's output dtype (a
    training-loss knob); the serving `lm` loader must still put float32
    logits on the wire."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from kubeflow_tpu.models.transformer import Transformer, TransformerConfig
    from kubeflow_tpu.serving.export import export
    from kubeflow_tpu.serving.model_server import ModelServer

    overrides = {
        "vocab_size": 64, "d_model": 16, "n_layers": 1, "n_heads": 2,
        "n_kv_heads": 2, "d_ff": 32, "head_dim": 8, "max_seq_len": 16,
        "dtype": "bfloat16", "ce_dtype": "compute",
    }
    cfg = TransformerConfig(**{**overrides, "dtype": jnp.bfloat16})
    model = Transformer(cfg)
    variables = model.init(jax.random.key(0), jnp.zeros((1, 4), jnp.int32))
    assert model.apply(variables, jnp.zeros((1, 4), jnp.int32)).dtype \
        == jnp.bfloat16  # the knob really does change the forward dtype
    export(str(tmp_path / "lm"), 1, variables,
           loader="kubeflow_tpu.serving.loaders:lm", config=overrides)
    server = ModelServer()
    server.add_model("lm", str(tmp_path / "lm"))
    out = server.predict("lm", {"tokens": np.asarray([[1, 2, 3]], np.int32)})
    assert np.asarray(out["logits"]).dtype == np.float32


class TestResumeAndStreaming:
    """Survivable-inference engine surface (PR 14): a resume admission
    (prompt + tokens a prior attempt delivered) must be token-identical
    to an uninterrupted generate() at EVERY cut point — including cuts
    landing mid-speculative-window and under a tight paged-KV pool —
    and the streaming surface must emit exactly the suffix."""

    def _engine(self, spec, decode=None, name="test-resume", **kw):
        from kubeflow_tpu.serving.engine import DecodeEngine

        kw.setdefault("slots", 2)
        kw.setdefault("prefill_len", 24)
        kw.setdefault("prefill_chunk_tokens", 8)
        kw.setdefault("kv_block_tokens", 4)
        return DecodeEngine(spec["cfg"], spec["params"],
                            decode or spec["decode"], name=name, **kw)

    def test_resume_matches_generate_at_every_cut(self, engine_model):
        spec, _ = engine_model
        prompt = _prompt()
        want = _reference_rows(spec, [prompt], [NEW_TOKENS])[0]
        suffix = want[len(prompt):]
        engine = self._engine(spec, name="test-resume-cuts")
        try:
            for cut in range(NEW_TOKENS):
                out = engine.submit({
                    "tokens": np.asarray(prompt, np.int32),
                    "resume_tokens": suffix[:cut],
                    "max_new_tokens": NEW_TOKENS})
                got = np.asarray(out["tokens"])[0].tolist()
                assert got == want, (
                    f"resume at cut {cut} drifted: {got} != {want}")
            # A resume whose tokens already spend the whole budget is
            # a COMPLETED generation (the prior attempt died between
            # its last token and the done marker): resolved
            # immediately, nothing re-generated.
            stats_before = engine.stats()["requests"]
            out = engine.submit({
                "tokens": np.asarray(prompt, np.int32),
                "resume_tokens": suffix,
                "max_new_tokens": NEW_TOKENS})
            assert np.asarray(out["tokens"])[0].tolist() == want
            assert engine.stats()["requests"] == stats_before
        finally:
            engine.close()

    def test_resume_ending_at_eos_is_complete(self, engine_model):
        import dataclasses

        spec, _ = engine_model
        prompt = _prompt()
        want = _reference_rows(spec, [prompt], [NEW_TOKENS])[0]
        suffix = want[len(prompt):]
        # Declare the 4th continuation token EOS: an uninterrupted run
        # stops there, so a resume carrying it is already complete.
        eos = suffix[3]
        decode = dataclasses.replace(spec["decode"], eos_token=eos)
        engine = self._engine(spec, decode=decode,
                              name="test-resume-eos")
        try:
            out = engine.submit({
                "tokens": np.asarray(prompt, np.int32),
                "resume_tokens": suffix[:4],
                "max_new_tokens": NEW_TOKENS})
            got = np.asarray(out["tokens"])[0].tolist()
            assert got == prompt + suffix[:4]
        finally:
            engine.close()

    def test_resume_mid_speculative_window_identity(self, engine_model):
        """A resume landing mid-speculative-window: the resumed engine
        drafts from the identical history (prompt + resume), so the
        suffix must still be bit-identical to the uninterrupted run."""
        spec, _ = engine_model
        rng = np.random.RandomState(SEED + 31)
        pat = rng.randint(1, VOCAB, size=(4,))
        prompt = np.tile(pat, 3).tolist()  # repetitive: drafts fire
        want = _reference_rows(spec, [prompt], [NEW_TOKENS])[0]
        suffix = want[len(prompt):]
        engine = self._engine(spec, speculative_tokens=4,
                              prefill_len=32,
                              name="test-resume-spec")
        try:
            for cut in (2, 5, 9):
                out = engine.submit({
                    "tokens": np.asarray(prompt, np.int32),
                    "resume_tokens": suffix[:cut],
                    "max_new_tokens": NEW_TOKENS})
                got = np.asarray(out["tokens"])[0].tolist()
                assert got == want, (
                    f"speculative resume at cut {cut} drifted")
        finally:
            engine.close()

    def test_resume_under_tight_kv_pool(self, engine_model):
        """Resume admissions reserve worst-case pages like any other:
        under a pool barely covering one worst case they serialize
        (never deadlock) and stay token-identical."""
        import threading

        spec, _ = engine_model
        prompt = _prompt()
        want = _reference_rows(spec, [prompt], [NEW_TOKENS])[0]
        suffix = want[len(prompt):]
        # Worst case: ceil((8 prompt + 6 resume + 6 new) / 4) = 5
        # pages; pool of 6 fits ONE resumed request plus scraps.
        engine = self._engine(spec, kv_pool_blocks=6,
                              name="test-resume-tight")
        try:
            outs = [None] * 3

            def client(i):
                outs[i] = engine.submit({
                    "tokens": np.asarray(prompt, np.int32),
                    "resume_tokens": suffix[:6],
                    "max_new_tokens": NEW_TOKENS})

            threads = [threading.Thread(target=client, args=(i,))
                       for i in range(3)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
            for i, out in enumerate(outs):
                assert out is not None, f"client {i} hung"
                assert np.asarray(out["tokens"])[0].tolist() == want
        finally:
            engine.close()
        assert engine.stats()["kv_blocks_used"] == 0

    def test_submit_stream_yields_exact_suffix(self, engine_model):
        spec, _ = engine_model
        prompt = _prompt()
        want = _reference_rows(spec, [prompt], [NEW_TOKENS])[0]
        engine = self._engine(spec, name="test-stream")
        try:
            meta, it = engine.submit_stream(
                {"tokens": np.asarray(prompt, np.int32),
                 "max_new_tokens": NEW_TOKENS})
            assert meta["resumable"] is True  # greedy export
            assert meta["seeded"] is False
            assert meta["prompt_tokens"] == len(prompt)
            assert meta["max_new_tokens"] == NEW_TOKENS
            got = []
            for chunk in it:
                assert chunk, "empty emission chunk"
                got.extend(chunk)
            assert got == want[len(prompt):]
            # Stream + resume: only the post-cut suffix is emitted.
            meta, it = engine.submit_stream(
                {"tokens": np.asarray(prompt, np.int32),
                 "resume_tokens": want[len(prompt):len(prompt) + 5],
                 "max_new_tokens": NEW_TOKENS})
            assert meta["prompt_tokens"] == len(prompt) + 5
            got = [t for chunk in it for t in chunk]
            assert got == want[len(prompt) + 5:]
        finally:
            engine.close()

    def test_rest_generate_route_streams_ndjson(self, engine_model):
        """The :generate route end to end over a real socket: chunked
        NDJSON with a meta line, token lines totaling the reference
        continuation, and a done line — plus the resume payload."""
        import http.client

        from kubeflow_tpu.serving.http import make_http_server
        from kubeflow_tpu.serving.main import batcher_factory

        spec, server = engine_model
        want = _reference_rows(spec, [_prompt()], [NEW_TOKENS])[0]
        server.enable_batching("lm", batcher_factory(
            micro_batch_size=0, batch_timeout_s=0.005,
            lm_engine=True, lm_engine_slots=2,
            lm_engine_prefill_len=24))
        httpd = None
        try:
            httpd, _ = make_http_server(server, port=0,
                                        host="127.0.0.1")
            port = httpd.server_address[1]

            def stream(body):
                conn = http.client.HTTPConnection(
                    "127.0.0.1", port, timeout=60)
                conn.request("POST", "/model/lm:generate",
                             json.dumps(body).encode(),
                             {"Content-Type": "application/json"})
                resp = conn.getresponse()
                status = resp.status
                msgs = []
                if status == 200:
                    while True:
                        line = resp.readline()
                        if not line:
                            break
                        line = line.strip()
                        if not line:
                            continue
                        msgs.append(json.loads(line))
                        if "done" in msgs[-1] or "error" in msgs[-1]:
                            break
                else:
                    msgs = [json.loads(resp.read() or b"{}")]
                conn.close()
                return status, msgs

            status, msgs = stream({"tokens": _prompt(),
                                   "max_new_tokens": NEW_TOKENS})
            assert status == 200
            assert msgs[0]["meta"]["resumable"] is True
            assert msgs[0]["meta"]["model"] == "lm"
            toks = [t for m in msgs for t in m.get("tokens", [])]
            assert toks == want[PROMPT_LEN:]
            assert msgs[-1] == {"done": True,
                                "tokens_emitted": NEW_TOKENS}
            # Resume over the wire: only the suffix streams back.
            status, msgs = stream({
                "tokens": _prompt(),
                "resume_tokens": want[PROMPT_LEN:PROMPT_LEN + 4],
                "max_new_tokens": NEW_TOKENS})
            assert status == 200
            toks = [t for m in msgs for t in m.get("tokens", [])]
            assert toks == want[PROMPT_LEN + 4:]
            # Bad request: a missing tokens key answers a plain 400
            # BEFORE any stream bytes.
            status, msgs = stream({"max_new_tokens": 4})
            assert status == 400, msgs
        finally:
            if httpd is not None:
                httpd.shutdown()
            server.enable_batching("lm", lambda model: None)

    def test_generate_requires_engine(self, engine_model):
        """Without a streaming batching plane the route is a client
        error, not a hang: the static batchers dispatch whole
        generations and cannot stream."""
        from kubeflow_tpu.serving.http import ServingAPI

        spec, server = engine_model
        api = ServingAPI(server)  # no batcher enabled: direct path
        with pytest.raises(ValueError, match="streaming"):
            api.generate("lm", {"tokens": _prompt()})
        with pytest.raises(KeyError):
            api.generate("nope", {"tokens": _prompt()})
