"""The deterministic fault-injection harness (testing/faults.py):
grammar, scripted actions, seeded probability, the policy clock, and
the install/uninstall lifecycle.  The serving-side behavior the harness
drives lives in tests/test_fault_tolerance.py."""

import threading
import time

import pytest

from kubeflow_tpu.testing import faults


class TestGrammar:
    def test_parse_actions_times_prob_seed(self):
        inj = faults.parse(
            "seed=7; engine.step:sleep=0.05*3@0.5 ;loader.load:raise;"
            "clock.site:skew=2.5*1")
        specs = inj._specs
        s = specs["engine.step"][0]
        assert (s.action, s.value, s.times, s.prob) == \
            ("sleep", 0.05, 3, 0.5)
        s = specs["loader.load"][0]
        assert (s.action, s.value, s.times, s.prob) == \
            ("raise", 0.0, -1, 1.0)
        s = specs["clock.site"][0]
        assert (s.action, s.value, s.times) == ("skew", 2.5, 1)

    def test_bad_entries_rejected(self):
        with pytest.raises(ValueError, match="site:action"):
            faults.parse("just-a-site")
        with pytest.raises(ValueError, match="unknown fault action"):
            faults.parse("x:explode")

    def test_empty_entries_ignored(self):
        inj = faults.parse(";;seed=3;;")
        assert inj._specs == {}


class TestFiring:
    def test_raise_action_and_times_bound(self):
        inj = faults.parse("x:raise*2")
        for _ in range(2):
            with pytest.raises(faults.FaultInjected):
                inj.fire("x")
        inj.fire("x")  # budget spent: passes through
        assert inj.fired("x") == 3  # encounters, not firings

    def test_encounters_counted_without_spec(self):
        # Production hooks at sites with no spec still count — tests
        # use this to prove code did NOT reach a hook (breaker open).
        inj = faults.parse("seed=1")
        inj.fire("loader.load")
        assert inj.fired("loader.load") == 1

    def test_sleep_action_blocks(self):
        inj = faults.parse("x:sleep=0.05*1")
        t0 = time.perf_counter()
        inj.fire("x")
        assert time.perf_counter() - t0 >= 0.04
        t0 = time.perf_counter()
        inj.fire("x")  # budget spent
        assert time.perf_counter() - t0 < 0.04

    def test_seeded_probability_is_replayable(self):
        def run():
            inj = faults.parse("seed=42;x:raise@0.5")
            hits = []
            for _ in range(32):
                try:
                    inj.fire("x")
                    hits.append(0)
                except faults.FaultInjected:
                    hits.append(1)
            return hits

        first, second = run(), run()
        assert first == second
        assert 0 < sum(first) < 32  # actually probabilistic


class TestPolicyClock:
    def test_skew_action_and_advance_clock(self):
        inj = faults.parse("x:skew=5*1")
        base = time.monotonic()
        assert abs(inj.monotonic() - base) < 1.0
        inj.fire("x")
        assert inj.monotonic() - time.monotonic() >= 4.9
        inj.advance_clock(10)
        assert inj.monotonic() - time.monotonic() >= 14.9

    def test_module_monotonic_tracks_installed_injector(self):
        assert faults.active() is None
        before = faults.monotonic()
        assert abs(before - time.monotonic()) < 1.0
        with faults.injected("seed=0") as inj:
            inj.advance_clock(100)
            assert faults.monotonic() - time.monotonic() >= 99
        assert abs(faults.monotonic() - time.monotonic()) < 1.0


class TestLifecycle:
    def test_injected_context_restores_previous(self):
        outer = faults.parse("a:raise")
        faults.install(outer)
        try:
            with faults.injected("b:raise") as inner:
                assert faults.active() is inner
                with pytest.raises(faults.FaultInjected):
                    faults.fire("b")
            assert faults.active() is outer
        finally:
            faults.install(None)

    def test_module_fire_is_noop_when_uninstalled(self):
        assert faults.active() is None
        faults.fire("anything")  # must not raise

    def test_install_from_env(self):
        inj = faults.install_from_env({"KFT_FAULTS": "x:raise*1"})
        try:
            assert faults.active() is inj
            with pytest.raises(faults.FaultInjected):
                faults.fire("x")
        finally:
            faults.install(None)
        assert faults.install_from_env({}) is None
        assert faults.active() is None

    def test_thread_safety_of_counts(self):
        inj = faults.parse("seed=0")
        threads = [threading.Thread(
            target=lambda: [inj.fire("x") for _ in range(200)])
            for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert inj.fired("x") == 800
