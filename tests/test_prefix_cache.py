"""Paged-KV block manager (serving/prefix_cache.py): refcounted
physical allocation, token-reservation admission, block-hashed
zero-copy prefix aliasing, LRU eviction — and a randomized invariant
battery over a seeded mixed workload (the allocator must never
double-free, never alias a page to two diverged writers, and free
everything on release+invalidate)."""

import numpy as np
import pytest

from kubeflow_tpu.serving.prefix_cache import BlockManager


def toks(*vals):
    return np.asarray(vals, np.int32)


def run_request(mgr, tokens, budget):
    """One request's whole pool lifecycle, the way the engine drives
    it: admit (alias + reserve worst case), take every reserved page,
    publish the full-block prefix, release.  Returns (blocks, cached,
    res) with the pages still HELD (caller releases)."""
    need = -(-(len(tokens) + budget) // mgr.block)
    plan = mgr.admit(np.asarray(tokens, np.int32), len(tokens) - 1, need)
    if plan is None:
        return None
    shared, cached = plan
    blocks = list(shared)
    res = need - len(shared)
    while len(blocks) < need:
        blocks.append(mgr.take())
        res -= 1
    mgr.publish(np.asarray(tokens, np.int32), len(tokens), blocks)
    return blocks, cached, res


class TestBlockManager:
    def test_admit_reserve_take_release_roundtrip(self):
        mgr = BlockManager(num_blocks=8, block_tokens=2)
        plan = mgr.admit(toks(1, 2, 3, 4), 3, 4)
        assert plan == ([], 0)  # cold: no alias, 4 reserved
        assert mgr.available() == 4
        blocks = [mgr.take() for _ in range(4)]
        assert len(set(blocks)) == 4
        assert mgr.used_blocks() == 4
        mgr.release(blocks)
        assert mgr.used_blocks() == 0
        assert mgr.available() == 8
        mgr.check_invariants()

    def test_take_without_reservation_is_a_bug(self):
        mgr = BlockManager(num_blocks=2, block_tokens=2)
        with pytest.raises(RuntimeError):
            mgr.take()

    def test_admission_refused_when_pool_cannot_cover(self):
        mgr = BlockManager(num_blocks=4, block_tokens=2)
        assert mgr.admit(toks(1, 2), 1, 3) is not None
        # 1 block of headroom left; a 2-block request must hold.
        assert mgr.admit(toks(3, 4), 1, 2) is None
        # ... until the first request unreserves.
        mgr.release([], unreserve=3)
        assert mgr.admit(toks(3, 4), 1, 2) is not None
        mgr.check_invariants()

    def test_longest_block_prefix_aliases_zero_copy(self):
        mgr = BlockManager(num_blocks=16, block_tokens=2)
        out = run_request(mgr, [1, 2, 3, 4, 5, 6], 2)
        blocks, cached, res = out
        assert cached == 0
        # Full three-block prefix published; a sharer aliases the SAME
        # physical pages (zero-copy is literal: identical block ids).
        plan = mgr.admit(toks(1, 2, 3, 4, 5, 6, 7), 6, 4)
        shared, cached2 = plan
        assert cached2 == 6 and shared == blocks[:3]
        # limit forces >= 1 recomputed token: only 2 blocks match.
        plan = mgr.admit(toks(1, 2, 3, 4, 5, 6), 5, 3)
        assert plan[1] == 4 and plan[0] == blocks[:2]
        # Divergence after one block aliases one block (chained
        # digests: a shared MIDDLE block never matches alone).
        plan = mgr.admit(toks(1, 2, 9, 9), 3, 2)
        assert plan[1] == 2 and plan[0] == blocks[:1]
        plan = mgr.admit(toks(9, 2, 3, 4), 3, 2)
        assert plan == ([], 0)
        mgr.check_invariants()

    def test_partial_trailing_block_never_published(self):
        mgr = BlockManager(num_blocks=8, block_tokens=4)
        run_request(mgr, [1, 2, 3, 4, 5, 6], 2)
        plan = mgr.admit(toks(1, 2, 3, 4, 5, 6, 7, 8), 7, 2)
        assert plan[1] == 4  # only the full block matched

    def test_aliased_pages_survive_writer_release(self):
        """The capturing request retires while a sharer still aliases
        the pages: they must stay resident (refcount), and free only
        when BOTH the sharer and the record let go."""
        mgr = BlockManager(num_blocks=4, block_tokens=2)
        blocks, _, res = run_request(mgr, [1, 2, 3, 4], 0)
        shared, cached = mgr.admit(toks(1, 2, 3, 4), 3, 2)
        assert cached == 2 and shared == blocks[:1]
        mgr.release(blocks, unreserve=res)  # writer gone
        mgr.check_invariants()
        # The aliased page is still resident (sharer + record hold it).
        assert shared[0] not in mgr._free
        mgr.release(shared, unreserve=2 - len(shared))
        mgr.check_invariants()
        # Record-held pages remain as evictable cache, not leaked.
        assert mgr.used_blocks() == 2  # the two published pages
        mgr.invalidate()
        assert mgr.used_blocks() == 0

    def test_lru_eviction_frees_only_unreferenced(self):
        mgr = BlockManager(num_blocks=4, block_tokens=2)
        a, _, ra = run_request(mgr, [1, 1, 1, 1], 0)
        mgr.release(a, unreserve=ra)
        b, _, rb = run_request(mgr, [2, 2, 2, 2], 0)
        mgr.release(b, unreserve=rb)
        # Pool full of cached pages; a fresh 2-block request must evict
        # the LRU record (a's) — b's stays.
        plan = mgr.admit(toks(3, 3, 3, 3), 3, 2)
        assert plan == ([], 0)
        c = [mgr.take(), mgr.take()]
        assert mgr.evictions == 1 and mgr.block_evictions == 2
        assert set(c) == set(a)  # a's pages were recycled
        assert mgr.admit(toks(1, 1, 1, 1), 3, 0) == ([], 0)  # a gone
        plan = mgr.admit(toks(2, 2, 2, 2), 3, 2)
        assert plan[1] == 2  # b still served
        mgr.check_invariants()

    def test_record_evicted_mid_use_keeps_pages_resident(self):
        mgr = BlockManager(num_blocks=4, block_tokens=2)
        a, _, ra = run_request(mgr, [1, 1, 1, 1], 0)
        mgr.release(a, unreserve=ra)
        shared, cached = mgr.admit(toks(1, 1, 1, 1), 3, 1)
        assert cached == 2
        # Force eviction pressure (a 3-block request against 2 free
        # pages): the record dies, but the page the sharer still
        # aliases must NOT free out from under it.
        b, _, rb = run_request(mgr, [2, 2, 2, 2, 2, 2], 0)
        assert mgr.evictions == 1
        assert mgr.block_evictions == 1  # only the unreferenced page
        for blk in shared:
            assert blk not in mgr._free
        mgr.release(shared)
        mgr.release(b, unreserve=rb)
        mgr.check_invariants()

    def test_digest_collision_first_writer_wins(self):
        mgr = BlockManager(num_blocks=8, block_tokens=2)
        a, _, ra = run_request(mgr, [1, 2, 3, 4], 0)
        # Cache OFF lookup path for the duplicate: publish the same
        # chain from different physical pages (racing captures).
        plan = mgr.admit(toks(9, 9, 9, 9), 3, 2)
        dup = [mgr.take(), mgr.take()]
        mgr.publish(toks(1, 2, 3, 4), 4, dup)
        # The established record keeps serving the digests.
        shared, cached = mgr.admit(toks(1, 2, 3, 4, 5), 4, 3)
        assert cached == 4 and shared == a[:2]
        mgr.release(shared, unreserve=1)
        mgr.release(a, unreserve=ra)
        mgr.release(dup)
        mgr.check_invariants()

    def test_caching_off_is_pure_allocator(self):
        mgr = BlockManager(num_blocks=4, block_tokens=2, caching=False)
        blocks, cached, res = run_request(mgr, [1, 2, 3, 4], 0)
        assert cached == 0
        mgr.release(blocks, unreserve=res)
        assert mgr.admit(toks(1, 2, 3, 4), 3, 2) == ([], 0)
        assert mgr.used_blocks() == 0  # publish was a no-op
        mgr.check_invariants()

    def test_rollback_restores_reservation(self):
        mgr = BlockManager(num_blocks=4, block_tokens=2)
        mgr.admit(toks(1, 2), 1, 3)
        blocks = [mgr.take() for _ in range(3)]
        assert mgr.available() == 1
        mgr.rollback(blocks[2:])  # speculative tail trim
        assert mgr.available() == 1  # page freed, reservation restored
        assert mgr.take() == blocks[2]
        mgr.release(blocks)
        mgr.check_invariants()

    def test_invalidate_forgets_everything(self):
        mgr = BlockManager(num_blocks=4, block_tokens=2)
        blocks, _, res = run_request(mgr, [1, 2, 3, 4], 0)
        mgr.release(blocks, unreserve=res)
        shared, cached = mgr.admit(toks(1, 2, 3, 4), 3, 1)
        assert cached == 2
        mgr.release(shared)  # the sharer retires before the reload
        mgr.invalidate()
        assert mgr.admit(toks(1, 2, 3, 4), 3, 0) == ([], 0)
        assert mgr.used_blocks() == 0
        assert mgr.stats()["published_records"] == 0
        mgr.check_invariants()

    def test_validation(self):
        with pytest.raises(ValueError):
            BlockManager(num_blocks=0, block_tokens=2)
        with pytest.raises(ValueError):
            BlockManager(num_blocks=1, block_tokens=0)


class TestAllocatorInvariantBattery:
    """Seeded randomized mixed workload against a small pool: admit /
    grow / speculative-rollback / release / publish in arbitrary
    interleavings.  After EVERY operation the structural invariants
    must hold (no double-free, refcount/free-list agreement,
    reservation coverage), no page may ever be writable by two
    diverged requests at once, and a full drain + invalidate must
    return every page."""

    @pytest.mark.parametrize("seed", [0, 1, 7])
    def test_mixed_workload_never_corrupts(self, seed):
        rng = np.random.RandomState(seed)
        mgr = BlockManager(num_blocks=12, block_tokens=4)
        live = []  # dicts: tokens, blocks, shared_n, res_left, need

        def writable(req):
            # Pages this request may WRITE: its private (taken) pages.
            # Aliased prefix pages are read-only by construction — the
            # engine starts its first write at the block-aligned
            # cached offset, which always lands in a private page.
            return set(req["blocks"][req["shared_n"]:])

        for _ in range(400):
            op = rng.randint(4)
            if op == 0 and len(live) < 6:  # admit
                # Half the prompts share one of two hot prefixes so
                # aliasing actually happens; suffixes diverge.
                base = ([1, 2, 3, 4, 5, 6, 7, 8] if rng.randint(2)
                        else [9, 9, 9, 9])
                tokens = (base * 2)[:rng.randint(4, 13)] + \
                    rng.randint(10, 90, size=(rng.randint(0, 5),)
                                ).tolist()
                budget = int(rng.randint(1, 9))
                need = -(-(len(tokens) + budget) // mgr.block)
                plan = mgr.admit(np.asarray(tokens, np.int32),
                                 len(tokens) - 1, need)
                if plan is not None:
                    shared, cached = plan
                    assert cached <= len(tokens) - 1
                    assert len(shared) * mgr.block == cached
                    live.append({
                        "tokens": tokens, "blocks": list(shared),
                        "shared_n": len(shared),
                        "res_left": need - len(shared), "need": need,
                        "published": False})
            elif op == 1 and live:  # grow the frontier
                req = live[rng.randint(len(live))]
                if req["res_left"] > 0:
                    blk = mgr.take()
                    req["res_left"] -= 1
                    # Exclusive ownership at take(): no other live
                    # request may hold (let alone write) this page.
                    for other in live:
                        if other is not req:
                            assert blk not in other["blocks"], (
                                "page aliased to a diverged writer")
                    req["blocks"].append(blk)
                    if not req["published"] and (
                            len(req["blocks"]) * mgr.block
                            >= len(req["tokens"])):
                        mgr.publish(
                            np.asarray(req["tokens"], np.int32),
                            len(req["tokens"]), req["blocks"])
                        req["published"] = True
            elif op == 2 and live:  # speculative tail rollback
                req = live[rng.randint(len(live))]
                private_n = len(req["blocks"]) - req["shared_n"]
                if private_n > 1:
                    tail = req["blocks"][-1:]
                    del req["blocks"][-1:]
                    req["res_left"] += 1
                    mgr.rollback(tail)
            elif op == 3 and live:  # retire
                req = live.pop(rng.randint(len(live)))
                mgr.release(req["blocks"], unreserve=req["res_left"])
            # Writable sets of any two live requests stay disjoint.
            for i, a in enumerate(live):
                for b in live[i + 1:]:
                    assert not (writable(a) & writable(b))
            mgr.check_invariants()

        for req in live:
            mgr.release(req["blocks"], unreserve=req["res_left"])
        mgr.check_invariants()
        mgr.invalidate()
        assert mgr.used_blocks() == 0, "pages leaked after full drain"
        assert mgr.available() == mgr.num_blocks

    @pytest.mark.parametrize("seed", [0, 1, 7])
    def test_spill_workload_never_corrupts(self, seed):
        """The two-tier battery (§5.10): the device workload above
        interleaved with spill / park (host_put) / fetch
        (lookup_spilled) ops against a small host tier, invariants
        checked across BOTH tiers after every op.  Host records hold
        COPIES keyed by the same chained digests — never device block
        ids — so a page can never be device-writable and host-spilled
        at once; the payload marker asserts lookups return the exact
        record stored for that chain depth."""
        rng = np.random.RandomState(seed)
        mgr = BlockManager(num_blocks=12, block_tokens=4,
                           host_blocks=8)
        live = []
        spilled_chains = []  # (tokens, depth_blocks) once host-stored

        def writable(req):
            return set(req["blocks"][req["shared_n"]:])

        def payload_for(digests):
            return {"marker": digests[-1], "n": len(digests)}

        for _ in range(400):
            op = rng.randint(7)
            if op == 0 and len(live) < 6:  # admit
                base = ([1, 2, 3, 4, 5, 6, 7, 8] if rng.randint(2)
                        else [9, 9, 9, 9])
                tokens = (base * 2)[:rng.randint(4, 13)] + \
                    rng.randint(10, 90, size=(rng.randint(0, 5),)
                                ).tolist()
                budget = int(rng.randint(1, 9))
                need = -(-(len(tokens) + budget) // mgr.block)
                plan = mgr.admit(np.asarray(tokens, np.int32),
                                 len(tokens) - 1, need)
                if plan is not None:
                    shared, cached = plan
                    live.append({
                        "tokens": tokens, "blocks": list(shared),
                        "shared_n": len(shared),
                        "res_left": need - len(shared), "need": need,
                        "published": False})
            elif op == 1 and live:  # grow the frontier
                req = live[rng.randint(len(live))]
                if req["res_left"] > 0:
                    blk = mgr.take()
                    req["res_left"] -= 1
                    for other in live:
                        if other is not req:
                            assert blk not in other["blocks"], (
                                "page aliased to a diverged writer")
                    req["blocks"].append(blk)
                    if not req["published"] and (
                            len(req["blocks"]) * mgr.block
                            >= len(req["tokens"])):
                        mgr.publish(
                            np.asarray(req["tokens"], np.int32),
                            len(req["tokens"]), req["blocks"])
                        req["published"] = True
            elif op == 2 and live:  # speculative tail rollback
                req = live[rng.randint(len(live))]
                if len(req["blocks"]) - req["shared_n"] > 1:
                    tail = req["blocks"][-1:]
                    del req["blocks"][-1:]
                    req["res_left"] += 1
                    mgr.rollback(tail)
            elif op == 3 and live:  # retire
                req = live.pop(rng.randint(len(live)))
                mgr.release(req["blocks"], unreserve=req["res_left"])
            elif op == 4:  # spill an idle LRU record to the host tier
                for rec in mgr.spill_candidates(max_records=2):
                    digests = list(rec.digests)
                    freed = mgr.spill(rec, payload_for(digests))
                    if freed is None:
                        continue  # declined: stale or unstorable
                    # The freed pages are back in the free list — a
                    # double-free of any of them would trip
                    # check_invariants' free-list uniqueness below.
                    assert 0 <= freed <= len(digests)
                    assert digests[-1] in mgr._host_chains
            elif op == 5:  # park a session's KV straight to host
                tokens = rng.randint(1, 90,
                                     size=(rng.randint(4, 17),)).tolist()
                depth = len(tokens) // mgr.block
                if depth:
                    dig = payload_for(
                        [b"x"] * depth)  # marker only needs depth
                    stored = mgr.host_put(
                        np.asarray(tokens, np.int32), len(tokens),
                        {"marker": None, "n": depth})
                    if stored:
                        spilled_chains.append((tokens, stored))
            elif op == 6 and spilled_chains:  # fetch / re-import path
                tokens, depth = spilled_chains[
                    rng.randint(len(spilled_chains))]
                payload, got = mgr.lookup_spilled(
                    np.asarray(tokens, np.int32), len(tokens))
                if payload is not None:  # may have been host-evicted
                    assert 0 < got <= depth
                    assert payload["n"] >= got
                    mgr.spills_in += got  # the engine's re-import
            for i, a in enumerate(live):
                for b in live[i + 1:]:
                    assert not (writable(a) & writable(b))
            mgr.check_invariants()

        for req in live:
            mgr.release(req["blocks"], unreserve=req["res_left"])
        mgr.check_invariants()
        mgr.invalidate()
        assert mgr.used_blocks() == 0, "pages leaked after full drain"
        assert mgr.host_used_blocks() == 0, "host pages survived drain"
        assert mgr.available() == mgr.num_blocks

    def test_spill_preserves_available_and_declines_unstorable(self):
        """Spilling an idle record moves its pages cached->free, so
        available() is UNCHANGED (the deadlock-freedom invariant
        free + evictable + spillable >= reserved holds across tiers)
        — and a record larger than the whole host tier is declined
        outright rather than destroying the only copy."""
        mgr = BlockManager(num_blocks=8, block_tokens=4, host_blocks=2)
        tokens = toks(*range(1, 13))  # 3 full blocks
        got = run_request(mgr, tokens, budget=0)
        assert got is not None
        blocks, _, res = got
        mgr.release(blocks, unreserve=res)
        before = mgr.available()
        [rec] = [r for r in (mgr.spill_candidates(2) or [])] or [None]
        # 3 blocks > host_blocks=2: candidates must skip it entirely
        # (the pages still count as spillable mass — they are idle —
        # but no candidate offers them, so the engine destroy-evicts).
        assert rec is None
        assert mgr.spillable_blocks() == 3
        # Enlarge the tier: now it spills, available() is unchanged.
        mgr.host_blocks = 4
        [rec] = mgr.spill_candidates(1)
        freed = mgr.spill(rec, {"p": 1})
        assert freed == 3
        assert mgr.available() == before
        assert mgr.host_used_blocks() == 3
        payload, depth = mgr.lookup_spilled(tokens, len(tokens))
        assert payload == {"p": 1} and depth == 3
        mgr.check_invariants()
