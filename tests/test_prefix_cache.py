"""Host-side prefix index (serving/prefix_cache.py): block-hashed
longest-prefix lookup, LRU + refcount eviction, and the invariants the
DecodeEngine's shared-prefix reuse leans on."""

import numpy as np
import pytest

from kubeflow_tpu.serving.prefix_cache import PrefixIndex


def toks(*vals):
    return np.asarray(vals, np.int32)


class TestPrefixIndex:
    def test_longest_block_prefix_match(self):
        idx = PrefixIndex(rows=2, block_tokens=2, pool_len=8)
        row, evicted = idx.begin_capture()
        assert (row, evicted) == (0 if row == 0 else row, False)
        published = idx.commit_capture(row, toks(1, 2, 3, 4, 5, 6), 6)
        assert published == 6  # three full blocks
        # Full three-block match, capped by limit.
        assert idx.lookup(toks(1, 2, 3, 4, 5, 6, 7), limit=6) == (row, 6)
        # limit forces at least one recomputed token: only 2 blocks fit.
        assert idx.lookup(toks(1, 2, 3, 4, 5, 6), limit=5) == (row, 4)
        # Divergence after one block matches one block.
        assert idx.lookup(toks(1, 2, 9, 9, 9, 9), limit=6) == (row, 2)
        # Different first block: no match (chained digests — a shared
        # MIDDLE block must not match).
        assert idx.lookup(toks(9, 2, 3, 4), limit=4) == (None, 0)
        # Sub-block prefixes can't match.
        assert idx.lookup(toks(1, 2), limit=1) == (None, 0)

    def test_partial_trailing_block_never_published(self):
        idx = PrefixIndex(rows=1, block_tokens=4, pool_len=16)
        row, _ = idx.begin_capture()
        assert idx.commit_capture(row, toks(*range(1, 7)), 6) == 4
        assert idx.lookup(toks(*range(1, 9)), limit=7) == (row, 4)

    def test_lru_eviction_prefers_least_recently_used(self):
        idx = PrefixIndex(rows=2, block_tokens=2, pool_len=4)
        a, _ = idx.begin_capture()
        idx.commit_capture(a, toks(1, 1), 2)
        b, _ = idx.begin_capture()
        idx.commit_capture(b, toks(2, 2), 2)
        # Touch A so B becomes LRU.
        assert idx.lookup(toks(1, 1, 3), limit=2) == (a, 2)
        c, evicted = idx.begin_capture()
        assert evicted and c == b
        idx.commit_capture(c, toks(3, 3), 2)
        assert idx.evictions == 1
        assert idx.lookup(toks(2, 2, 9), limit=2) == (None, 0)  # gone
        assert idx.lookup(toks(1, 1, 9), limit=2) == (a, 2)     # kept

    def test_pinned_rows_never_evicted(self):
        idx = PrefixIndex(rows=1, block_tokens=2, pool_len=4)
        row, _ = idx.begin_capture()
        # Mid-capture (pinned, uncommitted): the only row is pinned, so
        # a second capture must be refused, not steal it.
        assert idx.begin_capture() == (None, False)
        idx.commit_capture(row, toks(5, 5), 2)
        # Committed rows are unpinned and evictable again.
        row2, evicted = idx.begin_capture()
        assert row2 == row and evicted

    def test_abort_returns_row_without_publishing(self):
        idx = PrefixIndex(rows=1, block_tokens=2, pool_len=4)
        row, _ = idx.begin_capture()
        idx.abort_capture(row)
        assert idx.lookup(toks(1, 1, 1), limit=2) == (None, 0)
        row2, evicted = idx.begin_capture()
        assert row2 == row and not evicted  # free again, no eviction

    def test_too_short_commit_is_released(self):
        idx = PrefixIndex(rows=1, block_tokens=4, pool_len=8)
        row, _ = idx.begin_capture()
        assert idx.commit_capture(row, toks(1, 2, 3), 3) == 0
        row2, evicted = idx.begin_capture()
        assert row2 == row and not evicted

    def test_invalidate_forgets_everything(self):
        idx = PrefixIndex(rows=2, block_tokens=2, pool_len=4)
        row, _ = idx.begin_capture()
        idx.commit_capture(row, toks(1, 2, 3, 4), 4)
        assert idx.lookup(toks(1, 2, 3, 4, 5), limit=4)[1] == 4
        idx.invalidate()
        assert idx.lookup(toks(1, 2, 3, 4, 5), limit=4) == (None, 0)
        assert idx.stats()["committed_rows"] == 0
        # All rows are allocatable again.
        assert idx.begin_capture()[0] is not None
        assert idx.begin_capture()[0] is not None

    def test_digest_collision_first_writer_wins(self):
        """Two rows committing the SAME prefix (racing captures of one
        hot prompt): the established row keeps serving its digests, so
        evicting the duplicate later cannot orphan the prefix."""
        idx = PrefixIndex(rows=2, block_tokens=2, pool_len=4)
        a, _ = idx.begin_capture()
        idx.commit_capture(a, toks(1, 2, 3, 4), 4)
        b, _ = idx.begin_capture()
        idx.commit_capture(b, toks(1, 2, 3, 4), 4)  # duplicate chain
        assert idx.lookup(toks(1, 2, 3, 4, 5), limit=4) == (a, 4)
        # Evict b (a was just touched, so b is LRU) — the prefix must
        # survive because b never owned its digests.
        c, evicted = idx.begin_capture()
        assert evicted and c == b
        idx.commit_capture(c, toks(7, 8), 2)
        assert idx.lookup(toks(1, 2, 3, 4, 5), limit=4) == (a, 4)

    def test_validation(self):
        with pytest.raises(ValueError):
            PrefixIndex(rows=0, block_tokens=2, pool_len=4)
        with pytest.raises(ValueError):
            PrefixIndex(rows=1, block_tokens=0, pool_len=4)
