"""Full-loop E2E: the real operator daemon loop and the real-cluster E2E
drivers exercised TOGETHER against one shared control plane.

Round-2 gap (VERDICT #7): `operator/main.py`'s loop and
`testing/e2e.py deploy-crds`/`tpujob-real` were each tested only against
their own isolated stub.  Here one FakeKube plays the cluster for both
sides at once — the reference's deploy-then-submit-then-poll loop
(testing/test_deploy.py:160-190 + the simple_tfjob check) with three
real actors:

  * the TPUJobController reconcile loop (the exact object
    operator/main.py constructs), running on its own thread;
  * a fake kubelet driving created pods Pending -> Running -> Succeeded,
    standing in for the containers a kind/GKE cluster would run —
    docker/kind are unavailable in this build environment (see
    BASELINE.md), so container execution is the one simulated piece;
  * the unmodified e2e.py drivers, whose kubectl shell-outs are routed
    onto the same FakeKube by a translating stub.
"""

import json
import threading
import time

import pytest
import yaml

from kubeflow_tpu.operator.gang import GangScheduler
from kubeflow_tpu.operator.kube import (
    FAILED,
    PENDING,
    RUNNING,
    SUCCEEDED,
    FakeKube,
    NotFound,
)
from kubeflow_tpu.operator.reconciler import TPUJobController
from kubeflow_tpu.testing import e2e


class KubectlStub:
    """Translate the e2e drivers' kubectl invocations onto a FakeKube.

    Only the verbs the drivers use: create namespace, apply -f -, and
    get tpujobs <name> -o json.  Anything else is a test bug."""

    def __init__(self, kube: FakeKube):
        self.kube = kube
        self.applied = []

    def __call__(self, args, *, input_text=None, timeout=300):
        if args[:2] == ["create", "namespace"]:
            return ""
        if args[0] == "apply":
            for doc in yaml.safe_load_all(input_text or ""):
                if not doc:
                    continue
                self.applied.append(doc)
                if doc.get("kind") == "TPUJob":
                    self.kube.create_custom(doc)
            return ""
        if args[0] == "get" and args[1].startswith("tpujobs"):
            name, namespace = args[2], args[args.index("-n") + 1]
            try:
                return json.dumps(self.kube.get_custom(namespace, name))
            except NotFound:
                raise RuntimeError(f"tpujob {name} not found")
        raise AssertionError(f"unexpected kubectl verb: {args}")


@pytest.fixture()
def cluster():
    """Shared FakeKube + operator loop + fake kubelet, started/stopped
    around each test."""
    kube = FakeKube()
    controller = TPUJobController(
        kube, GangScheduler({"v5e-1": 2, "v5e-8": 4}))
    stop = threading.Event()

    def operator_loop():
        # The daemon loop operator/main.py runs, bounded per iteration so
        # the stop flag is honored.
        while not stop.is_set():
            controller.run(poll_interval_s=0.0, max_iterations=1)
            time.sleep(0.02)

    def kubelet_loop():
        # Stand-in for container execution (no docker/kind here): every
        # scheduled pod runs briefly, then exits 0.
        seen = {}
        while not stop.is_set():
            for key, pod in list(kube.pods.items()):
                phase = pod["status"]["phase"]
                ns, name = key
                if phase == PENDING:
                    kube.set_pod_phase(ns, name, RUNNING)
                    seen[key] = time.monotonic()
                elif phase == RUNNING and \
                        time.monotonic() - seen.get(key, 0) > 0.1:
                    kube.set_pod_phase(ns, name, SUCCEEDED)
            time.sleep(0.02)

    threads = [threading.Thread(target=operator_loop, daemon=True),
               threading.Thread(target=kubelet_loop, daemon=True)]
    for t in threads:
        t.start()
    yield kube
    stop.set()
    for t in threads:
        t.join(timeout=5)


class TestFullLoop:
    def test_deploy_crds_then_tpujob_real_succeeds(self, cluster,
                                                   monkeypatch):
        stub = KubectlStub(cluster)
        monkeypatch.setattr(e2e, "_kubectl", stub)
        monkeypatch.setenv("KFT_E2E_SLICE", "v5e-1")

        e2e.deploy_crds(namespace="kubeflow-test")
        assert any(d.get("kind") == "CustomResourceDefinition"
                   for d in stub.applied)

        e2e.tpujob_real(namespace="kubeflow-test")
        cr = cluster.get_custom("kubeflow-test", "e2e-smoke")
        assert cr["status"]["phase"] == "Succeeded"
        # The operator really created gang pods for the job.
        assert any("e2e-smoke" in name
                   for (_, name) in cluster.pods.keys())

    def test_failed_worker_surfaces_failure(self, cluster, monkeypatch):
        """The loop also propagates failure: a pod that exits nonzero
        after max restarts drives the CR to Failed, and tpujob-real's
        assertion trips — the E2E would catch a broken operator."""
        stub = KubectlStub(cluster)
        monkeypatch.setattr(e2e, "_kubectl", stub)
        monkeypatch.setenv("KFT_E2E_SLICE", "v5e-1")

        # Sabotage the kubelet: flip every running pod to Failed.
        def saboteur():
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                for (ns, name), pod in list(cluster.pods.items()):
                    if pod["status"]["phase"] in (PENDING, RUNNING):
                        cluster.set_pod_phase(ns, name, FAILED)
                time.sleep(0.01)

        t = threading.Thread(target=saboteur, daemon=True)
        t.start()
        e2e.deploy_crds(namespace="kubeflow-test")
        # tpujob_real's poll breaks on any terminal phase and asserts
        # Succeeded — a Failed CR trips it without waiting out the
        # 10-minute budget.
        with pytest.raises(AssertionError, match="Failed"):
            e2e.tpujob_real(namespace="kubeflow-test")
