"""Golden-style tests for the widened manifest packages (serving,
tensorboard, iap, addons, examples, torch) — heir of the reference's
jsonnet assertion suites (kubeflow/core/tests/*.jsonnet, SURVEY.md §4)."""

import pytest
import yaml

import kubeflow_tpu.manifests  # noqa: F401 — registers prototypes
from kubeflow_tpu.config.registry import App, default_registry
from kubeflow_tpu.manifests.base import to_yaml
from kubeflow_tpu.manifests.iap import is_cloud_endpoint


EXPECTED_PROTOTYPES = {
    "argo", "cert-manager", "cloud-endpoints", "gcp-credentials-pod-preset",
    "iap-ingress", "jupyterhub", "kubeflow-core", "pachyderm", "seldon",
    "tensorboard", "torch-xla-job", "tpu-cnn-benchmark", "tpu-job",
    "tpu-job-simple", "tpu-serving", "tpu-serving-simple",
    "tpu-serving-with-istio", "tpujob-operator",
}


def test_registry_has_all_packages():
    assert EXPECTED_PROTOTYPES <= set(default_registry.names())


def kinds(objs):
    return [o["kind"] for o in objs]


class TestServing:
    def test_default_render(self):
        objs = default_registry.generate("tpu-serving", "mnist",
                                         model_name="mnist")
        assert kinds(objs) == ["Deployment", "Service"]
        deploy, svc = objs
        args = deploy["spec"]["template"]["spec"]["containers"][0]["args"]
        assert "--model_name=mnist" in args
        assert "getambassador.io/config" in svc["metadata"]["annotations"]
        route = svc["metadata"]["annotations"]["getambassador.io/config"]
        assert "/models/mnist/" in route

    def test_s3_mixin_env(self):
        objs = default_registry.generate(
            "tpu-serving", "m", storage_type="s3")
        env = objs[0]["spec"]["template"]["spec"]["containers"][0]["env"]
        names = {e["name"] for e in env}
        assert {"AWS_ACCESS_KEY_ID", "AWS_SECRET_ACCESS_KEY", "AWS_REGION",
                "S3_USE_HTTPS", "S3_VERIFY_SSL", "S3_ENDPOINT"} <= names
        keyed = [e for e in env if e["name"] == "AWS_ACCESS_KEY_ID"][0]
        assert keyed["valueFrom"]["secretKeyRef"]["name"] == "s3-credentials"

    def test_gcp_mixin_mount(self):
        objs = default_registry.generate(
            "tpu-serving", "m", storage_type="gcp")
        tmpl = objs[0]["spec"]["template"]["spec"]
        env = tmpl["containers"][0]["env"]
        assert any(e["name"] == "GOOGLE_APPLICATION_CREDENTIALS"
                   for e in env)
        assert tmpl["volumes"][0]["secret"]["secretName"] == "user-gcp-sa"

    def test_tpu_serving_gets_tpu_resources(self):
        objs = default_registry.generate(
            "tpu-serving", "m", slice_type="v5e-1")
        limits = objs[0]["spec"]["template"]["spec"]["containers"][0][
            "resources"]["limits"]
        assert limits == {"google.com/tpu": 1}

    def test_no_nvidia_gpu_anywhere(self):
        """BASELINE north-star: zero nvidia.com/gpu requests."""
        app = App()
        for proto in sorted(EXPECTED_PROTOTYPES):
            app.add(proto, f"x-{proto}")
        rendered = to_yaml(app.render())
        assert "nvidia.com/gpu" not in rendered


class TestTensorboard:
    def test_render(self):
        objs = default_registry.generate("tensorboard", "tb",
                                         log_dir="gs://bucket/logs",
                                         storage_type="gcp")
        deploy, svc = objs
        cmd = deploy["spec"]["template"]["spec"]["containers"][0]["command"]
        assert "--logdir=gs://bucket/logs" in cmd
        assert "/tensorboard/tb/" in \
            svc["metadata"]["annotations"]["getambassador.io/config"]


class TestIAP:
    def test_cloud_endpoint_detection(self):
        assert is_cloud_endpoint("kf.endpoints.proj.cloud.goog")
        assert not is_cloud_endpoint("kubeflow.example.com")

    def test_render_kinds(self):
        objs = default_registry.generate("iap-ingress", "platform")
        assert set(kinds(objs)) == {
            "BackendConfig", "ManagedCertificate", "Service", "Ingress",
            "Deployment",
        }
        ingress = [o for o in objs if o["kind"] == "Ingress"][0]
        assert ingress["spec"]["rules"][0]["host"].endswith("cloud.goog")


class TestAddons:
    def test_argo(self):
        objs = default_registry.generate("argo", "argo")
        assert "CustomResourceDefinition" in kinds(objs)
        crd = [o for o in objs if o["kind"] == "CustomResourceDefinition"][0]
        assert crd["spec"]["group"] == "argoproj.io"

    def test_seldon_and_pachyderm_render(self):
        for proto in ("seldon", "pachyderm"):
            objs = default_registry.generate(proto, proto)
            assert len(objs) >= 4

    def test_credentials_preset(self):
        objs = default_registry.generate(
            "gcp-credentials-pod-preset", "creds")
        assert objs[0]["kind"] == "PodPreset"
        env = objs[0]["spec"]["env"]
        assert env[0]["name"] == "GOOGLE_APPLICATION_CREDENTIALS"


class TestTorchProfile:
    def test_torch_job_is_tpujob_with_pjrt_env(self):
        objs = default_registry.generate("torch-xla-job", "bert")
        cr = objs[0]
        assert cr["kind"] == "TPUJob"
        env = cr["spec"]["worker"]["env"]
        assert env["PJRT_DEVICE"] == "TPU"
        assert env["XLA_USE_SPMD"] == "1"


class TestExamples:
    def test_job_simple(self):
        objs = default_registry.generate("tpu-job-simple", "hello")
        assert objs[0]["spec"]["sliceType"] == "v5e-1"

    def test_serving_simple_delegates(self):
        objs = default_registry.generate("tpu-serving-simple", "inception")
        assert kinds(objs) == ["Deployment", "Service"]

    def test_serving_with_istio(self):
        objs = default_registry.generate("tpu-serving-with-istio",
                                         "inception")
        assert kinds(objs) == ["Deployment", "Service", "DestinationRule",
                               "VirtualService"]


class TestServingIstio:
    """Heir of the RouteRule + sidecar-inject surface
    (kubeflow/tf-serving/tf-serving.libsonnet:287-305,
    examples/prototypes/tf-serving-with-istio.jsonnet:106)."""

    def test_sidecar_inject_and_version_label(self):
        objs = default_registry.generate(
            "tpu-serving", "m", istio_enable=True, istio_version="v2")
        deploy = objs[0]
        tmpl = deploy["spec"]["template"]
        assert tmpl["metadata"]["annotations"][
            "sidecar.istio.io/inject"] == "true"
        assert tmpl["metadata"]["labels"]["version"] == "v2"
        # Selector must stay version-free: it is immutable on the API
        # server, and the canary flow re-renders with a new version.
        assert "version" not in deploy["spec"]["selector"]["matchLabels"]
        svc = objs[1]
        assert "version" not in svc["spec"]["selector"]

    def test_route_objects_target_the_subset(self):
        objs = default_registry.generate(
            "tpu-serving", "m", istio_enable=True)
        dr = [o for o in objs if o["kind"] == "DestinationRule"][0]
        vs = [o for o in objs if o["kind"] == "VirtualService"][0]
        assert dr["spec"]["subsets"] == [
            {"name": "v1", "labels": {"version": "v1"}}]
        route = vs["spec"]["http"][0]["route"][0]
        assert route["destination"] == {"host": "m", "subset": "v1"}
        assert route["weight"] == 100

    def test_istio_off_by_default(self):
        objs = default_registry.generate("tpu-serving", "m")
        assert kinds(objs) == ["Deployment", "Service"]
        tmpl = objs[0]["spec"]["template"]["metadata"]["annotations"]
        # No istio injection by default; prometheus scrape annotations
        # are always present (pod + Service, either discovery mode).
        assert "sidecar.istio.io/inject" not in tmpl
        assert tmpl["prometheus.io/scrape"] == "true"
        assert objs[1]["metadata"]["annotations"][
            "prometheus.io/port"] == "8000"


class TestCertManager:
    """Heir of kubeflow/core/cert-manager.libsonnet:1-182."""

    def test_full_render(self):
        objs = default_registry.generate("cert-manager", "certs")
        ks = kinds(objs)
        assert ks.count("CustomResourceDefinition") == 3
        assert {"ServiceAccount", "ClusterRole", "ClusterRoleBinding",
                "Deployment", "Issuer"} <= set(ks)
        issuer = [o for o in objs if o["kind"] == "Issuer"][0]
        acme = issuer["spec"]["acme"]
        assert acme["server"].startswith("https://acme-v02")
        assert acme["solvers"] == [{"http01": {"ingress": {}}}]
        assert acme["privateKeySecretRef"]["name"] == \
            "letsencrypt-prod-secret"

    def test_crd_scopes(self):
        objs = default_registry.generate("cert-manager", "certs")
        scopes = {o["spec"]["names"]["kind"]: o["spec"]["scope"]
                  for o in objs
                  if o["kind"] == "CustomResourceDefinition"}
        assert scopes == {"Certificate": "Namespaced",
                          "Issuer": "Namespaced",
                          "ClusterIssuer": "Cluster"}

    def test_iap_cert_manager_tls(self):
        objs = default_registry.generate(
            "iap-ingress", "iap", tls_type="cert-manager",
            hostname="kf.example.com")
        cert = [o for o in objs if o["kind"] == "Certificate"][0]
        assert cert["apiVersion"] == "cert-manager.io/v1"
        assert cert["spec"]["dnsNames"] == ["kf.example.com"]
        ingress = [o for o in objs if o["kind"] == "Ingress"][0]
        # No ingress-shim annotation: the explicit Certificate is the
        # single owner of the TLS secret.
        assert "annotations" not in ingress["metadata"]
        assert ingress["spec"]["tls"] == [
            {"hosts": ["kf.example.com"],
             "secretName": "platform-cert-tls"}]

    def test_iap_rejects_unknown_tls_type(self):
        with pytest.raises(Exception):
            default_registry.generate("iap-ingress", "iap", tls_type="nope")


class TestCloudEndpoints:
    """Heir of kubeflow/core/cloud-endpoints.libsonnet:1-332."""

    def test_controller_render(self):
        objs = default_registry.generate("cloud-endpoints", "cloudep")
        ks = kinds(objs)
        assert ks == ["CustomResourceDefinition", "ServiceAccount",
                      "ClusterRole", "ClusterRoleBinding", "Deployment",
                      "Service"]
        deploy = [o for o in objs if o["kind"] == "Deployment"][0]
        c = deploy["spec"]["template"]["spec"]["containers"][0]
        env = {e["name"]: e["value"] for e in c["env"]}
        assert env["GOOGLE_APPLICATION_CREDENTIALS"] == \
            "/var/run/secrets/sa/sa-key.json"

    def test_hostname_renders_cr(self):
        objs = default_registry.generate(
            "cloud-endpoints", "cloudep",
            hostname="kubeflow.endpoints.myproj.cloud.goog")
        cr = [o for o in objs if o["kind"] == "CloudEndpoint"][0]
        assert cr["metadata"]["name"] == "kubeflow"
        assert cr["spec"]["project"] == "myproj"
        assert cr["spec"]["targetIngress"]["name"] == "iap-ingress"

    def test_non_cloud_goog_hostname_rejected(self):
        with pytest.raises(Exception):
            default_registry.generate("cloud-endpoints", "cloudep",
                                      hostname="kf.example.com")


class TestWholeAppRenders:
    def test_everything_is_valid_yaml(self):
        app = App()
        for proto in sorted(EXPECTED_PROTOTYPES):
            app.add(proto, f"c-{proto}")
        docs = list(yaml.safe_load_all(to_yaml(app.render())))
        assert len(docs) >= 30
        for doc in docs:
            assert "kind" in doc and "apiVersion" in doc
