"""Multi-tenant scheduler tests: quotas, weighted-fair ordering,
priority classes, provable backfill, preemption-with-resume, and the
anti-livelock rate limit.

Policy decisions are exercised two ways: directly against
``SchedulingPolicy.plan`` (a pure function of its snapshot — the unit
surface), and through the full ``TPUJobController`` + FakeKube loop
(the phases and status a user actually sees).
"""

import pytest

from kubeflow_tpu.operator import crd
from kubeflow_tpu.operator.gang import GangScheduler
from kubeflow_tpu.operator.kube import SUCCEEDED, FakeKube
from kubeflow_tpu.operator.reconciler import (
    JOB_FAILED,
    JOB_PREEMPTING,
    JOB_RUNNING,
    QUEUED,
    STARTING,
    TPUJobController,
)
from kubeflow_tpu.scheduler import (
    LABEL_FUSE_FAMILY,
    LABEL_PRIORITY,
    LABEL_TENANT,
    ClusterScheduler,
    JobView,
    PreemptionConfig,
    PreemptionRateLimiter,
    SchedulerConfig,
    SchedulingPolicy,
    colocate,
    fuse,
    pick_victims,
    tenant_shares,
)
from kubeflow_tpu.testing import faults


def view(key, tenant="default", priority="normal", slice_type="v5e-8",
         count=1, enqueued_at=0.0, phase="", prio_value=None):
    cfg = SchedulerConfig()
    chips_per = {"v5e-8": 8, "v5e-16": 16, "v5p-32": 16}[slice_type]
    return JobView(
        key=key, tenant=tenant, priority=priority,
        priority_value=(prio_value if prio_value is not None
                        else cfg.priority_value(priority)),
        slice_type=slice_type, count=count, chips=chips_per * count,
        phase=phase, enqueued_at=enqueued_at)


def make_cr(name, tenant="default", priority="normal",
            slice_type="v5e-8", num_slices=1):
    job = crd.TPUJobSpec(name=name, slice_type=slice_type,
                         num_slices=num_slices)
    cr = job.to_custom_resource()
    cr["metadata"]["labels"] = {LABEL_TENANT: tenant,
                                LABEL_PRIORITY: priority}
    return cr


@pytest.fixture()
def cluster():
    kube = FakeKube()
    gang = GangScheduler({"v5e-8": 4, "v5p-32": 1})
    config = SchedulerConfig(
        quotas={"greedy": {"v5e-8": 16}},
        preemption=PreemptionConfig(grace_period_s=5.0))
    sched = ClusterScheduler(gang, config)
    return kube, gang, sched, TPUJobController(kube, gang, sched)


def phases_by_name(kube):
    return {c["metadata"]["name"]: (c.get("status") or {})
            for c in kube.list_custom()}


class TestQuota:
    def test_quota_caps_concurrent_chips_per_tenant(self, cluster):
        kube, gang, sched, ctl = cluster
        # greedy: quota 16 chips of v5e-8 = 2 jobs of 8 chips.
        for i in range(3):
            kube.create_custom(make_cr(f"g{i}", tenant="greedy"))
        ctl.reconcile_all()
        st = phases_by_name(kube)
        starting = [n for n in st if st[n]["phase"] == STARTING]
        assert sorted(starting) == ["g0", "g1"]
        assert st["g2"]["phase"] == QUEUED
        assert st["g2"]["reason"] == "QuotaExceeded"
        assert "16" in st["g2"]["message"]

    def test_quota_blocked_job_does_not_wedge_other_tenants(self,
                                                            cluster):
        kube, gang, sched, ctl = cluster
        for i in range(3):
            kube.create_custom(make_cr(f"g{i}", tenant="greedy"))
        # Arrives AFTER the over-quota job; must still be admitted.
        kube.create_custom(make_cr("polite", tenant="polite"))
        ctl.reconcile_all()
        st = phases_by_name(kube)
        assert st["g2"]["reason"] == "QuotaExceeded"
        assert st["polite"]["phase"] == STARTING

    def test_quota_frees_on_completion(self, cluster):
        kube, gang, sched, ctl = cluster
        for i in range(3):
            kube.create_custom(make_cr(f"g{i}", tenant="greedy"))
        ctl.reconcile_all()
        for p in kube.list_pods(
                "kubeflow", labels={"kubeflow-tpu.org/job-name": "g0"}):
            kube.set_pod_phase("kubeflow", p["metadata"]["name"],
                               SUCCEEDED)
        ctl.reconcile_all()   # g0 Succeeded, claim released
        ctl.reconcile_all()   # g2 admitted inside the freed quota
        st = phases_by_name(kube)
        assert st["g0"]["phase"] == "Succeeded"
        assert st["g2"]["phase"] == STARTING

    def test_unlimited_without_config(self):
        policy = SchedulingPolicy(SchedulerConfig())
        pending = [view(f"ns/j{i}") for i in range(4)]
        plan = policy.plan(pending, [], {"v5e-8": 4}, {"v5e-8": 4})
        assert all(plan.decisions[j.key].action == "admit"
                   for j in pending)


class TestWeightedFair:
    def test_weights_interleave_tenants(self):
        """Tenant b (weight 3) gets ~3x tenant a (weight 1) of a
        contended pool, regardless of submission order."""
        config = SchedulerConfig(weights={"a": 1.0, "b": 3.0})
        policy = SchedulingPolicy(config)
        pending = (
            [view(f"ns/a{i}", tenant="a", enqueued_at=i)
             for i in range(3)] +
            [view(f"ns/b{i}", tenant="b", enqueued_at=10 + i)
             for i in range(3)])
        plan = policy.plan(pending, [], {"v5e-8": 4}, {"v5e-8": 4})
        admitted = [k for k in plan.order
                    if plan.decisions[k].action == "admit"]
        assert len(admitted) == 4
        by_tenant = {"a": 0, "b": 0}
        for key in admitted:
            by_tenant[key.split("/")[1][0]] += 1
        assert by_tenant == {"a": 1, "b": 3}

    def test_fifo_within_tenant_at_equal_priority(self):
        policy = SchedulingPolicy(SchedulerConfig())
        pending = [view(f"ns/j{i}", enqueued_at=float(i))
                   for i in (2, 0, 1)]
        plan = policy.plan(pending, [], {"v5e-8": 4}, {"v5e-8": 4})
        assert plan.order == ["ns/j0", "ns/j1", "ns/j2"]

    def test_strict_priority_across_fairness(self):
        """A high job is considered before normals even when its
        tenant is far above its fair share."""
        config = SchedulerConfig(weights={"hog": 1.0, "meek": 1.0})
        policy = SchedulingPolicy(config)
        running = [view(f"ns/r{i}", tenant="hog") for i in range(3)]
        pending = [view("ns/meek-normal", tenant="meek",
                        enqueued_at=0.0),
                   view("ns/hog-high", tenant="hog", priority="high",
                        enqueued_at=1.0)]
        plan = policy.plan(pending, running, {"v5e-8": 1},
                           {"v5e-8": 4})
        assert plan.order[0] == "ns/hog-high"
        assert plan.decisions["ns/hog-high"].action == "admit"

    def test_unknown_priority_class_degrades_to_default(self):
        config = SchedulerConfig()
        assert config.priority_value("no-such-class") == \
            config.priority_classes["normal"]


class TestBackfill:
    def test_cross_type_backfill_past_blocked_head(self, cluster):
        """FIFO would wedge the small v5e job behind the blocked v5p
        head; the policy layer lets it jump — disjoint pools, provably
        zero ETA impact."""
        kube, gang, sched, ctl = cluster
        kube.create_custom(make_cr("vp-run", priority="high",
                                   slice_type="v5p-32"))
        ctl.reconcile_all()
        kube.create_custom(make_cr("vp-blocked", priority="high",
                                   slice_type="v5p-32"))
        kube.create_custom(make_cr("small", priority="low"))
        ctl.reconcile_all()
        st = phases_by_name(kube)
        assert st["vp-blocked"]["phase"] == QUEUED
        assert st["vp-blocked"]["reason"] == "WaitingForSlices"
        assert st["small"]["phase"] == STARTING
        assert sched.status()["counters"]["backfilled"] >= 1

    def test_same_type_backfill_denied_when_blocked_on_capacity(self):
        """A same-type jump would add the jumper's claim to the set
        the blocked job waits on — not provably harmless, so denied.
        (Preemption off so the blocked high job stays a pure waiter.)"""
        policy = SchedulingPolicy(SchedulerConfig(
            preemption=PreemptionConfig(enable=False)))
        running = [view("ns/r0", count=2)]
        pending = [view("ns/big", priority="high", count=3,
                        enqueued_at=0.0),
                   view("ns/small", priority="low", count=1,
                        enqueued_at=1.0)]
        plan = policy.plan(pending, running, {"v5e-8": 2},
                           {"v5e-8": 4})
        assert plan.decisions["ns/big"].reason == "WaitingForSlices"
        assert plan.decisions["ns/small"].action == "wait"
        assert plan.decisions["ns/small"].reason == "BackfillDenied"

    def test_backfill_never_delays_blocked_jobs_eta(self, cluster):
        """The blocked head starts the moment its own capacity frees,
        with the backfilled job still running — ETA unchanged."""
        kube, gang, sched, ctl = cluster
        kube.create_custom(make_cr("vp-run", priority="high",
                                   slice_type="v5p-32"))
        ctl.reconcile_all()
        kube.create_custom(make_cr("vp-blocked", priority="high",
                                   slice_type="v5p-32"))
        kube.create_custom(make_cr("small", priority="low"))
        ctl.reconcile_all()
        # vp-run finishes; the backfilled small job keeps running.
        for p in kube.list_pods(
                "kubeflow",
                labels={"kubeflow-tpu.org/job-name": "vp-run"}):
            kube.set_pod_phase("kubeflow", p["metadata"]["name"],
                               SUCCEEDED)
        ctl.reconcile_all()
        ctl.reconcile_all()
        st = phases_by_name(kube)
        assert st["vp-blocked"]["phase"] == STARTING
        assert st["small"]["phase"] == STARTING

    def test_cross_type_backfill_marked(self):
        policy = SchedulingPolicy(SchedulerConfig(enable_backfill=True))
        pending = [view("ns/big", priority="high",
                        slice_type="v5p-32", enqueued_at=0.0),
                   view("ns/small", enqueued_at=1.0)]
        plan = policy.plan(pending, [view("ns/r", slice_type="v5p-32",
                                          phase="Running")],
                           {"v5p-32": 0, "v5e-8": 1},
                           {"v5p-32": 1, "v5e-8": 1})
        assert plan.decisions["ns/small"].action == "admit"
        assert plan.decisions["ns/small"].backfilled

    def test_backfill_disabled_by_config(self):
        """enableBackfill:false restores head-of-line: a fitting job
        behind any blocked head waits, even cross-type."""
        policy = SchedulingPolicy(SchedulerConfig(
            enable_backfill=False,
            preemption=PreemptionConfig(enable=False)))
        pending = [view("ns/big", priority="high",
                        slice_type="v5p-32", enqueued_at=0.0),
                   view("ns/small", enqueued_at=1.0)]
        plan = policy.plan(pending, [view("ns/r", slice_type="v5p-32",
                                          phase="Running")],
                           {"v5p-32": 0, "v5e-8": 1},
                           {"v5p-32": 1, "v5e-8": 1})
        assert plan.decisions["ns/small"].action == "wait"
        assert plan.decisions["ns/small"].reason == "BackfillDenied"

    def test_quota_impossible_demand_is_unsatisfiable(self):
        """A job whose demand exceeds its tenant's quota outright can
        NEVER run under this config — terminal, like the capacity
        path, not a permanent queue squatter."""
        policy = SchedulingPolicy(SchedulerConfig(
            quotas={"t": {"v5e-8": 16}}))
        pending = [view("ns/too-big", tenant="t", count=3)]  # 24 chips
        plan = policy.plan(pending, [], {"v5e-8": 4}, {"v5e-8": 4})
        decision = plan.decisions["ns/too-big"]
        assert decision.action == "unsatisfiable"
        assert decision.reason == "QuotaUnsatisfiable"


class TestPreemptionPolicy:
    def test_victim_selection_lowest_priority_then_fewest_chips(self):
        running = [view("ns/norm", priority="normal", count=1),
                   view("ns/low-big", priority="low", count=2),
                   view("ns/low-small", priority="low", count=1)]
        preemptor = view("ns/vip", priority="high", count=2)
        victims = pick_victims(running, preemptor, free=0)
        assert [v.key for v in victims] == ["ns/low-small",
                                            "ns/low-big"]

    def test_no_partial_eviction_when_insufficient(self):
        """Lower-priority victims that cannot free enough capacity are
        left alone — evicting them would burn checkpoints without
        unblocking the preemptor."""
        running = [view("ns/low", priority="low", count=1)]
        preemptor = view("ns/vip", priority="high", count=4)
        assert pick_victims(running, preemptor, free=0) == []

    def test_equal_priority_never_evicted(self):
        running = [view("ns/peer", priority="high", count=4)]
        preemptor = view("ns/vip", priority="high", count=4)
        assert pick_victims(running, preemptor, free=0) == []

    def test_rate_limiter_window(self):
        with faults.injected("seed=1") as inj:
            limiter = PreemptionRateLimiter(max_preemptions=2,
                                            window_s=60.0)
            assert limiter.allow()
            limiter.record()
            limiter.record()
            assert not limiter.allow()
            inj.advance_clock(61)
            assert limiter.allow()

    def test_rate_limited_plan_defers_eviction(self):
        config = SchedulerConfig(preemption=PreemptionConfig(
            max_preemptions=1, window_s=300.0))
        policy = SchedulingPolicy(config)
        running = [view("ns/low-a", priority="low"),
                   view("ns/low-b", priority="low")]
        pending = [view("ns/hi-a", priority="high", enqueued_at=0.0),
                   view("ns/hi-b", priority="high", enqueued_at=1.0)]
        with faults.injected("seed=1"):
            plan = policy.plan(pending, running, {"v5e-8": 0},
                               {"v5e-8": 2})
        assert len(plan.preemptions) == 1
        reasons = sorted(plan.decisions[k].reason
                         for k in ("ns/hi-a", "ns/hi-b"))
        assert reasons == ["PreemptionRateLimited",
                           "WaitingForPreemption"]

    def test_in_progress_eviction_absorbs_demand(self):
        """A blocked job covered by an eviction already in flight must
        wait for it, not trigger a second wave."""
        policy = SchedulingPolicy(SchedulerConfig())
        running = [view("ns/dying", priority="low",
                        phase="Preempting"),
                   view("ns/low2", priority="low")]
        pending = [view("ns/vip", priority="high")]
        plan = policy.plan(pending, running, {"v5e-8": 0},
                           {"v5e-8": 1})
        assert plan.preemptions == []
        assert plan.decisions["ns/vip"].reason == \
            "WaitingForPreemption"


class TestPreemptionLifecycle:
    def _fill_and_contest(self, kube, ctl):
        """4 low jobs fill v5e-8; a high job arrives."""
        for i in range(4):
            kube.create_custom(make_cr(f"low{i}", priority="low"))
        ctl.reconcile_all()
        kube.create_custom(make_cr("vip", priority="high",
                                   num_slices=1))
        ctl.reconcile_all()

    def test_grace_window_then_resumable_requeue(self, cluster):
        kube, gang, sched, ctl = cluster
        with faults.injected("seed=1") as inj:
            self._fill_and_contest(kube, ctl)
            st = phases_by_name(kube)
            victims = [n for n in st
                       if st[n]["phase"] == JOB_PREEMPTING]
            assert len(victims) == 1
            victim = victims[0]
            assert st[victim]["resumable"] is True
            assert st[victim]["preemptions"] == 1
            # Pods survive the grace window (checkpoint-on-SIGTERM).
            assert kube.list_pods(
                "kubeflow",
                labels={"kubeflow-tpu.org/job-name": victim})
            ctl.reconcile_all()
            assert phases_by_name(kube)[victim]["phase"] == \
                JOB_PREEMPTING
            inj.advance_clock(10)   # grace elapses on the policy clock
            ctl.reconcile_all()
            st = phases_by_name(kube)
            assert st[victim]["phase"] == QUEUED
            assert st[victim]["reason"] == "PreemptedRequeued"
            assert not kube.list_pods(
                "kubeflow",
                labels={"kubeflow-tpu.org/job-name": victim})
            ctl.reconcile_all()
            st = phases_by_name(kube)
            assert st["vip"]["phase"] == STARTING
            # restarts budget untouched: preemption is not a failure.
            assert int(st[victim].get("restarts", 0)) == 0

    def test_victim_resumes_after_preemptor_completes(self, cluster):
        kube, gang, sched, ctl = cluster
        with faults.injected("seed=1") as inj:
            self._fill_and_contest(kube, ctl)
            victim = [n for n, s in phases_by_name(kube).items()
                      if s["phase"] == JOB_PREEMPTING][0]
            inj.advance_clock(10)
            ctl.reconcile_all()
            ctl.reconcile_all()
            for p in kube.list_pods(
                    "kubeflow",
                    labels={"kubeflow-tpu.org/job-name": "vip"}):
                kube.set_pod_phase("kubeflow", p["metadata"]["name"],
                                   SUCCEEDED)
            ctl.reconcile_all()
            ctl.reconcile_all()
            st = phases_by_name(kube)
            assert st[victim]["phase"] == STARTING
            # The flag was CONSUMED by the resume admission (a later
            # ordinary restart must not count as another resume); the
            # preemption count survives as history.
            assert st[victim]["resumable"] is False
            assert st[victim]["preemptions"] == 1
            assert sched.status()["counters"]["resumed"] >= 1

    def test_no_livelock_between_flapping_priorities(self, cluster):
        """The resumed low job can never evict the high job back
        (victims are strictly lower priority), and repeated passes
        fire no further eviction waves."""
        kube, gang, sched, ctl = cluster
        with faults.injected("seed=1") as inj:
            self._fill_and_contest(kube, ctl)
            inj.advance_clock(10)
            ctl.reconcile_all()
            ctl.reconcile_all()
            before = sched.status()["counters"]["preempted"]
            for _ in range(5):
                ctl.reconcile_all()
            assert sched.status()["counters"]["preempted"] == before
            st = phases_by_name(kube)
            assert st["vip"]["phase"] in (STARTING, JOB_RUNNING)

    def test_gang_finishing_mid_grace_succeeds_not_requeued(self,
                                                            cluster):
        """A victim whose workers all succeed during the grace window
        completes normally — it must not be torn down, re-queued
        resumable, and re-run from checkpoint."""
        kube, gang, sched, ctl = cluster
        with faults.injected("seed=1") as inj:
            self._fill_and_contest(kube, ctl)
            st = phases_by_name(kube)
            victim = [n for n in st
                      if st[n]["phase"] == JOB_PREEMPTING][0]
            for p in kube.list_pods(
                    "kubeflow",
                    labels={"kubeflow-tpu.org/job-name": victim}):
                kube.set_pod_phase("kubeflow", p["metadata"]["name"],
                                   SUCCEEDED)
            ctl.reconcile_all()
            st = phases_by_name(kube)
            assert st[victim]["phase"] == "Succeeded", st[victim]
            # Slices freed without an eviction event; vip admits.
            assert sched.status()["counters"]["preempted"] == 0
            inj.advance_clock(60)   # stale grace must change nothing
            ctl.reconcile_all()
            st = phases_by_name(kube)
            assert st[victim]["phase"] == "Succeeded"
            assert st["vip"]["phase"] in (STARTING, JOB_RUNNING), st

    def test_gang_failure_mid_grace_cuts_grace_and_counts_restart(
            self, cluster):
        """A victim whose workers FAIL during the grace window is dead
        — nothing is checkpointing, so the grace is cut short, the
        failure consumes restart budget like any WorkerFailed, and the
        slices go to the preemptor immediately."""
        kube, gang, sched, ctl = cluster
        with faults.injected("seed=1"):
            self._fill_and_contest(kube, ctl)
            st = phases_by_name(kube)
            victim = [n for n in st
                      if st[n]["phase"] == JOB_PREEMPTING][0]
            pod = kube.list_pods(
                "kubeflow",
                labels={"kubeflow-tpu.org/job-name": victim})[0]
            kube.set_pod_phase("kubeflow", pod["metadata"]["name"],
                               "Failed")
            ctl.reconcile_all()   # no clock skew: grace NOT elapsed
            st = phases_by_name(kube)
            assert st[victim]["phase"] == QUEUED
            assert st[victim]["reason"] == "PreemptedRequeued"
            assert st[victim]["restarts"] == 1   # budget consumed
            assert st[victim]["resumable"] is True
            ctl.reconcile_all()
            assert phases_by_name(kube)["vip"]["phase"] == STARTING

    def test_eviction_cancelled_when_shortage_resolves_mid_grace(
            self, cluster):
        """The preemptor is deleted during the victim's grace window:
        the next plan withdraws the eviction and the victim keeps
        running — no teardown, no lost progress."""
        kube, gang, sched, ctl = cluster
        with faults.injected("seed=1"):
            self._fill_and_contest(kube, ctl)
            st = phases_by_name(kube)
            victim = [n for n in st
                      if st[n]["phase"] == JOB_PREEMPTING][0]
            kube.delete_custom("kubeflow", "vip")
            ctl.reconcile_all()
            st = phases_by_name(kube)
            assert st[victim]["phase"] in (STARTING, JOB_RUNNING), st
            # A later eviction starts a FRESH grace window, and the
            # eviction stamps are reverted — the job was never
            # actually preempted.
            assert victim not in ctl._preempt_deadline
            assert st[victim]["resumable"] is False
            assert st[victim]["preemptions"] == 0
            events = [e for e in kube.events
                      if e["reason"] == "PreemptionCancelled"]
            assert events, kube.events

    def test_plan_failure_mid_grace_holds_preempting(self, cluster):
        """A wedged plan pass while a victim is mid-grace must hold
        the eviction state, not flip the victim back to Running."""
        kube, gang, sched, ctl = cluster
        with faults.injected("seed=1"):
            self._fill_and_contest(kube, ctl)
            victim = [n for n, s in phases_by_name(kube).items()
                      if s["phase"] == JOB_PREEMPTING][0]
        with faults.injected("scheduler.admit:raise"):
            ctl.reconcile_all()
        assert phases_by_name(kube)[victim]["phase"] == JOB_PREEMPTING

    def test_resumed_job_restores_latest_checkpoint_step(self,
                                                         tmp_path):
        """The trainer-side half of the resume contract: the victim's
        checkpoint from before eviction is what restore_or_init hands
        back on re-admission — start_step > 0, no retraining."""
        import numpy as np

        from kubeflow_tpu.runtime.checkpoint import CheckpointManager

        base = np.arange(4, dtype=np.float32)
        with CheckpointManager(tmp_path / "ckpt",
                               save_interval_steps=1) as mgr:
            # The gang checkpoints through step 7, then is preempted.
            for step in range(8):
                mgr.save(step, {"step": np.full((), step, np.int32),
                                "w": base + step})
        # Re-admitted gang: fresh init, same directory.
        fresh = {"step": np.zeros((), np.int32),
                 "w": np.zeros(4, dtype=np.float32)}
        with CheckpointManager(tmp_path / "ckpt") as mgr2:
            restored, start = mgr2.restore_or_init(fresh)
        assert start == 8   # latest step + 1: past step-0
        assert int(restored["step"]) == 7
        np.testing.assert_allclose(restored["w"], base + 7)


class TestPlanAndStatus:
    def test_unsatisfiable_fails_fast_under_policy(self, cluster):
        kube, gang, sched, ctl = cluster
        kube.create_custom(make_cr("huge", num_slices=9))
        ctl.reconcile_all()
        st = phases_by_name(kube)["huge"]
        assert st["phase"] == JOB_FAILED
        assert st["reason"] == "UnsatisfiableResources"

    def test_plan_failure_holds_queue_not_running_jobs(self, cluster):
        """A wedged policy pass (scheduler.admit raise) keeps admitted
        gangs reconciling and parks pending jobs instead of falling
        back to FIFO admission."""
        kube, gang, sched, ctl = cluster
        kube.create_custom(make_cr("ok"))
        ctl.reconcile_all()
        kube.create_custom(make_cr("late"))
        with faults.injected("scheduler.admit:raise"):
            ctl.reconcile_all()
        st = phases_by_name(kube)
        assert st["ok"]["phase"] == STARTING
        assert st["late"]["phase"] == QUEUED
        assert st["late"]["reason"] == "WaitingForScheduler"
        # Next healthy pass admits it.
        ctl.reconcile_all()
        assert phases_by_name(kube)["late"]["phase"] == STARTING

    def test_status_payload_shape(self, cluster):
        kube, gang, sched, ctl = cluster
        for i in range(3):
            kube.create_custom(make_cr(f"g{i}", tenant="greedy"))
        ctl.reconcile_all()
        status = sched.status()
        by_job = {row["job"]: row for row in status["jobs"]}
        assert by_job["kubeflow/g0"]["state"] == "Admitted"
        assert by_job["kubeflow/g2"]["state"] == "QuotaExceeded"
        assert by_job["kubeflow/g2"]["wait_s"] is not None
        quota = status["quotas"][0]
        assert quota == {"tenant": "greedy", "slice_type": "v5e-8",
                         "used_chips": 16, "quota_chips": 16}

    def test_scheduler_metrics_exported(self, cluster):
        from kubeflow_tpu.runtime.prom import (
            REGISTRY,
            parse_metrics,
            sample_value,
        )

        kube, gang, sched, ctl = cluster
        for i in range(3):
            kube.create_custom(make_cr(f"g{i}", tenant="greedy"))
        ctl.reconcile_all()
        # Depth gauges export at PLAN time (start of the pass); the
        # second pass sees g0/g1 admitted and only g2 pending.
        ctl.reconcile_all()
        parsed = parse_metrics(REGISTRY.render())
        assert sample_value(parsed, "kft_scheduler_queue_depth",
                            tenant="greedy", priority="normal") == 1
        assert sample_value(parsed, "kft_scheduler_quota_used_chips",
                            tenant="greedy", slice_type="v5e-8") == 16
        assert sample_value(parsed, "kft_scheduler_quota_chips",
                            tenant="greedy", slice_type="v5e-8") == 16
        assert (sample_value(parsed, "kft_scheduler_admitted_total",
                             tenant="greedy") or 0) >= 2

    def test_config_from_dict_wire_shape(self):
        config = SchedulerConfig.from_dict({
            "quotas": {"a": {"v5e-8": 32}},
            "weights": {"a": 2.5},
            "priorityClasses": {"low": 0, "normal": 10, "high": 99},
            "enableBackfill": False,
            "preemption": {"grace_period_s": 12.5,
                           "max_preemptions": 2, "window_s": 60},
        })
        assert config.quotas == {"a": {"v5e-8": 32}}
        assert config.weight("a") == 2.5
        assert config.priority_value("high") == 99
        assert config.enable_backfill is False
        assert config.preemption.grace_period_s == 12.5
        with pytest.raises(ValueError, match="unknown scheduler"):
            SchedulerConfig.from_dict({"nope": 1})


class TestSchedulerSnapshotLockDiscipline:
    def test_note_calls_read_last_views_under_lock(self, cluster):
        """PR-8 lock-guard audit regression: plan() REBINDS
        _last_views under sched._lock; note_admitted/note_preempted
        must take the lock for their view lookup or the tenant label
        can come from a half-superseded snapshot."""
        kube, gang, sched, ctl = cluster

        class GuardedDict(dict):
            def __init__(self, lock):
                super().__init__()
                self.lock = lock
                self.bare_reads = []

            def get(self, key, default=None):
                if not self.lock.locked():
                    self.bare_reads.append(key)
                return super().get(key, default)

        guarded = GuardedDict(sched._lock)
        sched._last_views = guarded
        sched.note_admitted("default/j0")
        sched.note_preempted("default/j0")
        assert guarded.bare_reads == []


def fusable_cr(name, tenant="default", family="sweep",
               priority="normal"):
    cr = make_cr(name, tenant=tenant, priority=priority)
    cr["metadata"]["labels"][LABEL_FUSE_FAMILY] = family
    return cr


class TestFusedGangs:
    """Horizontal fusion (scheduler/fuse.py): fusable singleton swarms
    fold into ONE gang claim whose quota/fair-share bill is split
    per member tenant."""

    def test_tenant_shares_bills_member_share_not_whole_gang(self):
        """THE fair-share regression: before tenant_shares, every
        member of an N-way fused gang was billed the gang's FULL chip
        count, so a 4-member fuse charged each tenant 4x its real
        footprint and starved them out of their own quota."""
        solo = view("ns/solo")
        assert tenant_shares(solo) == [("default", 8.0)]
        members = [view(f"ns/m{i}", tenant=t) for i, t in
                   enumerate(["a", "a", "b", "b"])]
        for m in members:
            m.family = "sweep"
        plan_input, fused = fuse.fold_pending(members)
        assert len(plan_input) == 1 and len(fused) == 1
        shares = dict(tenant_shares(fused[0]))
        assert shares == {"a": 2.0, "b": 2.0}

    def test_fused_members_admit_within_quota_where_singletons_not(
            self, cluster):
        """greedy's 16-chip quota fits two 8-chip singletons — but all
        FOUR fusable singletons fused onto one slice (2 chips each)."""
        kube, gang, sched, ctl = cluster
        for i in range(4):
            kube.create_custom(fusable_cr(f"g{i}", tenant="greedy"))
        ctl.reconcile_all()
        st = phases_by_name(kube)
        assert all(st[f"g{i}"]["phase"] == STARTING for i in range(4))
        assert all(st[f"g{i}"]["fusedGang"] == "fused:kubeflow/sweep"
                   for i in range(4))
        assert gang.admitted("fused:kubeflow/sweep")
        # One shared pod gang under the fused workload name.
        assert kube.list_pods(
            "kubeflow",
            labels={"kubeflow-tpu.org/job-name": "fused-sweep"})
        quotas = {q["tenant"]: q["used_chips"]
                  for q in sched.status()["quotas"]}
        assert quotas["greedy"] == 8.0   # 4 members x 2 chips, not 32

    def test_status_rows_show_members_and_billed_share(self, cluster):
        kube, gang, sched, ctl = cluster
        for i in range(4):
            kube.create_custom(fusable_cr(f"g{i}", tenant="greedy"))
        ctl.reconcile_all()
        rows = {r["job"]: r for r in sched.status()["jobs"]}
        for i in range(4):
            row = rows[f"kubeflow/g{i}"]
            assert row["members"] == 4
            assert row["chips"] == 2.0

    def test_below_min_members_and_multislice_stay_singletons(self,
                                                              cluster):
        kube, gang, sched, ctl = cluster
        kube.create_custom(fusable_cr("only"))
        multi = make_cr("wide", num_slices=2)
        multi["metadata"]["labels"][LABEL_FUSE_FAMILY] = "sweep"
        kube.create_custom(multi)
        ctl.reconcile_all()
        st = phases_by_name(kube)
        assert not gang.admitted("fused:kubeflow/sweep")
        assert "fusedGang" not in st["only"]
        assert "fusedGang" not in st["wide"]

    def test_fused_gang_preempted_resumes_with_members(self):
        """vip evicts the fused gang; every member requeues resumable
        and the gang re-folds + resumes once vip completes."""
        kube = FakeKube()
        gang = GangScheduler({"v5e-8": 1})
        sched = ClusterScheduler(gang, SchedulerConfig(
            preemption=PreemptionConfig(grace_period_s=5.0)))
        ctl = TPUJobController(kube, gang, sched)
        with faults.injected("seed=1") as inj:
            for i in range(4):
                kube.create_custom(fusable_cr(f"m{i}", priority="low"))
            ctl.reconcile_all()
            assert gang.admitted("fused:kubeflow/sweep")
            kube.create_custom(make_cr("vip", priority="high"))
            ctl.reconcile_all()
            st = phases_by_name(kube)
            assert all(st[f"m{i}"]["phase"] == JOB_PREEMPTING
                       for i in range(4))
            inj.advance_clock(10)
            ctl.reconcile_all()
            st = phases_by_name(kube)
            for i in range(4):
                assert st[f"m{i}"]["phase"] == QUEUED
                assert st[f"m{i}"]["resumable"] is True
                assert st[f"m{i}"]["preemptions"] == 1
                assert not st[f"m{i}"]["fusedGang"]
            assert not gang.admitted("fused:kubeflow/sweep")
            ctl.reconcile_all()
            assert phases_by_name(kube)["vip"]["phase"] == STARTING
            for p in kube.list_pods(
                    "kubeflow",
                    labels={"kubeflow-tpu.org/job-name": "vip"}):
                kube.set_pod_phase("kubeflow", p["metadata"]["name"],
                                   SUCCEEDED)
            ctl.reconcile_all()
            ctl.reconcile_all()
            st = phases_by_name(kube)
            assert all(st[f"m{i}"]["phase"] == STARTING
                       for i in range(4))
            assert gang.admitted("fused:kubeflow/sweep")
            # Resume consumed each member's flag individually.
            assert sched.status()["counters"]["resumed"] == 4

    def test_fused_gang_completion_releases_claim_per_member(self,
                                                             cluster):
        kube, gang, sched, ctl = cluster
        for i in range(3):
            kube.create_custom(fusable_cr(f"m{i}"))
        ctl.reconcile_all()
        for p in kube.list_pods(
                "kubeflow",
                labels={"kubeflow-tpu.org/job-name": "fused-sweep"}):
            kube.set_pod_phase("kubeflow", p["metadata"]["name"],
                               SUCCEEDED)
        ctl.reconcile_all()
        st = phases_by_name(kube)
        assert all(st[f"m{i}"]["phase"] == "Succeeded"
                   for i in range(3))
        assert not gang.admitted("fused:kubeflow/sweep")
        completed = [e for e in kube.events
                     if e["reason"] == "FusedMemberCompleted"]
        assert len(completed) == 3


class TestColocation:
    """Train/serve colocation (scheduler/colocate.py): the serving
    Deployment's desired replicas as a high-priority claim on the
    SAME pool the training scheduler arbitrates."""

    def _mk(self, capacity=4, train_grace=30.0, serving_grace=5.0):
        kube = FakeKube()
        kube.create_deployment({
            "metadata": {"name": "lm", "namespace": "kubeflow"},
            "spec": {"replicas": 0}})
        gang = GangScheduler({"v5e-8": capacity})
        sched = ClusterScheduler(gang, SchedulerConfig(
            preemption=PreemptionConfig(
                grace_period_s=train_grace,
                serving_grace_period_s=serving_grace)))
        return kube, gang, sched, TPUJobController(kube, gang, sched)

    def test_claim_admits_and_reconciler_patches_deployment(self):
        kube, gang, sched, ctl = self._mk()
        kube.create_custom(colocate.build_claim_cr(
            "kubeflow", "lm", replicas=2))
        ctl.reconcile_all()
        st = phases_by_name(kube)
        assert st["serving-lm"]["phase"] == JOB_RUNNING
        assert st["serving-lm"]["reason"] == "ClaimGranted"
        assert st["serving-lm"]["grantedReplicas"] == 2
        # The RECONCILER patches replicas on grant — chips are held
        # before a replica rollout, never after.
        dep = kube.get_deployment("kubeflow", "lm")
        assert dep["spec"]["replicas"] == 2
        rows = {r["job"]: r for r in sched.status()["jobs"]}
        assert rows["kubeflow/serving-lm"]["kind"] == "serving-claim"
        assert rows["kubeflow/serving-lm"]["tenant"] == "fleet"
        pool = sched.status()["pool"]
        assert pool["capacity_chips"] == 32
        assert pool["serving_chips"] == 16
        assert pool["free_chips"] == 16

    def test_burst_preempts_training_on_short_grace(self):
        """A growing claim evicts low-priority training under the
        ordinary contract but with serving_grace_period_s — 6 s of
        clock skew ends the drain where the 30 s training grace would
        still be holding it."""
        kube, gang, sched, ctl = self._mk()
        with faults.injected("seed=1") as inj:
            for i in range(4):
                kube.create_custom(make_cr(f"low{i}", priority="low"))
            ctl.reconcile_all()
            kube.create_custom(colocate.build_claim_cr(
                "kubeflow", "lm", replicas=1))
            ctl.reconcile_all()
            st = phases_by_name(kube)
            victims = [n for n in st
                       if st[n]["phase"] == JOB_PREEMPTING]
            assert len(victims) == 1
            victim = victims[0]
            assert st[victim]["resumable"] is True
            inj.advance_clock(6)   # > serving grace, << training grace
            ctl.reconcile_all()
            st = phases_by_name(kube)
            assert st[victim]["phase"] == QUEUED
            assert st[victim]["reason"] == "PreemptedRequeued"
            # Eviction consumed no restart budget.
            assert int(st[victim].get("restarts", 0)) == 0
            ctl.reconcile_all()
            st = phases_by_name(kube)
            assert st["serving-lm"]["phase"] == JOB_RUNNING
            assert kube.get_deployment(
                "kubeflow", "lm")["spec"]["replicas"] == 1

    def test_grow_delta_competes_and_resizes_in_place(self):
        """Desired outgrowing the held claim queues only the DELTA;
        on grant the gang claim resizes — never a release/re-admit
        flap of the already-held slices."""
        kube, gang, sched, ctl = self._mk()
        with faults.injected("seed=1") as inj:
            kube.create_custom(colocate.build_claim_cr(
                "kubeflow", "lm", replicas=1))
            for i in range(3):
                kube.create_custom(make_cr(f"low{i}", priority="low"))
            ctl.reconcile_all()
            assert gang.claim_count("kubeflow/serving-lm") == 1
            # The autoscaler path: desired jumps to 3 (delete+create,
            # the CR API has no spec patch).
            client = colocate.ServingClaimClient(kube, "kubeflow", "lm")
            client.sync(3)
            ctl.reconcile_all()
            st = phases_by_name(kube)
            victims = [n for n in st
                       if st[n]["phase"] == JOB_PREEMPTING]
            assert len(victims) == 2
            # Mid-grace the claim still HOLDS its base slice.
            assert gang.claim_count("kubeflow/serving-lm") == 1
            assert st["serving-lm"]["phase"] == STARTING
            assert st["serving-lm"]["reason"] in (
                "ClaimGrowing", "WaitingForPreemption")
            inj.advance_clock(6)
            ctl.reconcile_all()
            ctl.reconcile_all()
            assert gang.claim_count("kubeflow/serving-lm") == 3
            assert kube.get_deployment(
                "kubeflow", "lm")["spec"]["replicas"] == 3
            assert client.observe()["state"] == "granted"

    def test_shrink_releases_and_training_backfills(self):
        kube, gang, sched, ctl = self._mk()
        kube.create_custom(colocate.build_claim_cr(
            "kubeflow", "lm", replicas=3))
        ctl.reconcile_all()
        for i in range(3):
            kube.create_custom(make_cr(f"t{i}", priority="low"))
        ctl.reconcile_all()
        st = phases_by_name(kube)
        admitted = [n for n in st if st[n].get("phase") == STARTING
                    and n.startswith("t")]
        assert len(admitted) == 1   # only 1 free slice
        colocate.ServingClaimClient(kube, "kubeflow", "lm").sync(1)
        ctl.reconcile_all()   # shrink releases, backfill same sweep
        ctl.reconcile_all()
        st = phases_by_name(kube)
        assert gang.claim_count("kubeflow/serving-lm") == 1
        assert all(st[f"t{i}"]["phase"] == STARTING for i in range(3))
        assert kube.get_deployment(
            "kubeflow", "lm")["spec"]["replicas"] == 1
        shrunk = [e for e in kube.events
                  if e["reason"] == "ClaimShrunk"]
        assert shrunk

    def test_scale_to_zero_deletes_claim_and_releases_chips(self):
        kube, gang, sched, ctl = self._mk()
        kube.create_custom(colocate.build_claim_cr(
            "kubeflow", "lm", replicas=2))
        ctl.reconcile_all()
        assert gang.admitted("kubeflow/serving-lm")
        client = colocate.ServingClaimClient(kube, "kubeflow", "lm")
        out = client.sync(0)
        assert out["state"] == "released"
        # The trough hands the deployment straight to zero (no
        # arbitration needed to RELEASE chips)...
        assert kube.get_deployment(
            "kubeflow", "lm")["spec"]["replicas"] == 0
        # ...and the reconciler's stale sweep frees the gang claim.
        ctl.reconcile_all()
        assert not gang.admitted("kubeflow/serving-lm")
        for i in range(4):
            kube.create_custom(make_cr(f"t{i}", priority="low"))
        ctl.reconcile_all()
        st = phases_by_name(kube)
        assert all(st[f"t{i}"]["phase"] == STARTING for i in range(4))

    def test_prepull_pods_pin_victim_nodes_then_retire(self):
        """Speculative placement: the sweep that starts a victim's
        drain drops prepull pods on its nodes; full grant retires
        them."""
        kube, gang, sched, ctl = self._mk()
        with faults.injected("seed=1") as inj:
            for i in range(4):
                kube.create_custom(make_cr(f"low{i}", priority="low"))
            ctl.reconcile_all()
            for i in range(4):
                for p in kube.list_pods(
                        "kubeflow",
                        labels={"kubeflow-tpu.org/job-name": f"low{i}"}):
                    kube.set_pod_node("kubeflow",
                                      p["metadata"]["name"],
                                      f"node-{i}")
            kube.create_custom(colocate.build_claim_cr(
                "kubeflow", "lm", replicas=1))
            ctl.reconcile_all()
            st = phases_by_name(kube)
            victim = [n for n in st
                      if st[n]["phase"] == JOB_PREEMPTING][0]
            vnode = f"node-{victim[-1]}"
            prepulls = kube.list_pods(
                "kubeflow",
                labels={colocate.LABEL_WORKLOAD:
                        colocate.WORKLOAD_PREPULL})
            assert [p["spec"]["nodeName"] for p in prepulls] == [vnode]
            # Requests nothing: a warmer can never steal the slice.
            assert prepulls[0]["spec"]["containers"][0][
                "resources"] == {}
            inj.advance_clock(6)
            ctl.reconcile_all()
            ctl.reconcile_all()
            assert phases_by_name(kube)["serving-lm"]["phase"] == \
                JOB_RUNNING
            # Retirement is level-triggered: the sweep AFTER the full
            # grant sees claim_count >= desired and reaps the warmers.
            ctl.reconcile_all()
            assert kube.list_pods(
                "kubeflow",
                labels={colocate.LABEL_WORKLOAD:
                        colocate.WORKLOAD_PREPULL}) == []

    def test_colocation_metrics_exported(self):
        from kubeflow_tpu.runtime.prom import (
            REGISTRY,
            parse_metrics,
            sample_value,
        )

        kube, gang, sched, ctl = self._mk()
        with faults.injected("seed=1") as inj:
            for i in range(4):
                kube.create_custom(make_cr(f"low{i}", priority="low"))
            ctl.reconcile_all()
            parsed = parse_metrics(REGISTRY.render())
            before = sample_value(
                parsed, "kft_scheduler_colocation_preemptions_total"
            ) or 0
            kube.create_custom(colocate.build_claim_cr(
                "kubeflow", "lm", replicas=1))
            ctl.reconcile_all()
            inj.advance_clock(6)
            ctl.reconcile_all()
            ctl.reconcile_all()
            # Gauges export at PLAN time: one more sweep sees the
            # admitted claim in its running set.
            ctl.reconcile_all()
            parsed = parse_metrics(REGISTRY.render())
            assert sample_value(
                parsed, "kft_scheduler_colocation_preemptions_total"
            ) == before + 1
            assert sample_value(
                parsed, "kft_scheduler_serving_claim_chips",
                claim="kubeflow/serving-lm") == 8

    def test_fold_and_claim_sync_are_fault_sites(self):
        kube, gang, sched, ctl = self._mk()
        kube.create_custom(colocate.build_claim_cr(
            "kubeflow", "lm", replicas=1))
        with faults.injected("scheduler.colocate:raise"):
            ctl.reconcile_all()   # wedged fold = wedged plan pass,
        st = phases_by_name(kube)  # contained: claim stays un-admitted
        assert st.get("serving-lm", {}).get("phase") in (None, QUEUED)
        assert not gang.admitted("kubeflow/serving-lm")
        client = colocate.ServingClaimClient(kube, "kubeflow", "lm")
        with faults.injected("autoscaler.claim:raise"):
            with pytest.raises(faults.FaultInjected):
                client.sync(2)
        ctl.reconcile_all()
        assert phases_by_name(kube)["serving-lm"]["phase"] == \
            JOB_RUNNING
