"""kubeflow-tpu CLI: the full ks-heir verb flow, including teardown.

The reference lifecycle was ``ks init/generate/param set/show/apply``
ending with ``ks delete`` (user_guide.md:366-410); every verb here runs
against a real app-state file in a tmpdir, with kubectl faked at the
subprocess boundary for the apply/delete hops.
"""

import json

import pytest
import yaml

from kubeflow_tpu.tools import cli


@pytest.fixture()
def app_file(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    path = str(tmp_path / "tpuflow.json")
    assert cli.main(["--app-file", path, "init",
                     "--namespace", "kubeflow"]) == 0
    assert cli.main(["--app-file", path, "generate",
                     "kubeflow-core", "core"]) == 0
    return path


def _fake_kubectl(monkeypatch, calls):
    class Proc:
        returncode = 0

    def run(cmd, input=None, **kw):
        calls.append((cmd, input))
        return Proc()

    monkeypatch.setattr(cli.subprocess, "run", run)


def test_workflow_state_is_inspectable(app_file):
    state = json.load(open(app_file))
    assert state["namespace"] == "kubeflow"
    assert state["components"][0]["prototype"] == "kubeflow-core"


def test_show_renders_yaml(app_file, capsys):
    assert cli.main(["--app-file", app_file, "show"]) == 0
    docs = list(yaml.safe_load_all(capsys.readouterr().out))
    assert any(d.get("kind") == "Deployment" for d in docs if d)


def test_delete_dry_run_prints_what_would_go(app_file, capsys):
    assert cli.main(["--app-file", app_file, "delete", "--dry-run"]) == 0
    docs = [d for d in yaml.safe_load_all(capsys.readouterr().out) if d]
    assert docs, "delete --dry-run must render the teardown set"
    # The app state survives teardown (delete is a cluster op, not an
    # app edit — the ks contract).
    assert json.load(open(app_file))["components"]


def test_delete_pipes_manifests_to_kubectl_delete(
        app_file, monkeypatch):
    calls = []
    _fake_kubectl(monkeypatch, calls)
    assert cli.main(["--app-file", app_file, "delete"]) == 0
    (cmd, manifest), = calls
    assert cmd[:3] == ["kubectl", "delete", "--ignore-not-found"]
    docs = [d for d in yaml.safe_load_all(manifest.decode()) if d]
    assert any(d.get("kind") == "Deployment" for d in docs)


def test_delete_single_component_only(app_file, monkeypatch):
    assert cli.main(["--app-file", app_file, "generate",
                     "tensorboard", "tb"]) == 0
    calls = []
    _fake_kubectl(monkeypatch, calls)
    assert cli.main(["--app-file", app_file, "delete", "tb"]) == 0
    (_, manifest), = calls
    # Only tb's manifests in the teardown set: core's gateway must not
    # be swept away by deleting an unrelated component.
    assert b"tensorboard" in manifest
    # (tb's Service still carries a getambassador.io route annotation;
    # what must be absent is core's ambassador Deployment itself.)
    assert b"name: ambassador" not in manifest


def test_delete_unknown_component_errors(app_file, capsys):
    assert cli.main(["--app-file", app_file, "delete", "nope"]) == 2
    assert "no component named" in capsys.readouterr().err


def test_apply_then_delete_round_trip(app_file, monkeypatch):
    """The full lifecycle: what apply ships, delete tears down —
    byte-identical manifest sets on both hops."""
    calls = []
    _fake_kubectl(monkeypatch, calls)
    assert cli.main(["--app-file", app_file, "apply"]) == 0
    assert cli.main(["--app-file", app_file, "delete"]) == 0
    (apply_cmd, applied), (delete_cmd, deleted) = calls
    assert apply_cmd[:2] == ["kubectl", "apply"]
    assert applied == deleted


def test_queue_status_renders_scheduler_table(capsys):
    """`kubeflow-tpu queue status` prints the operator scheduler's
    live queue/quota view (GET /queue on the metrics port)."""
    import json
    import threading
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    payload = {
        "jobs": [
            {"job": "kubeflow/train-a", "tenant": "prod",
             "priority": "high", "slices": "2xv5e-8", "chips": 16,
             "state": "Admitted", "detail": "", "position": None,
             "wait_s": None, "resumable": False, "preemptions": 0},
            {"job": "kubeflow/batch-7", "tenant": "batch",
             "priority": "low", "slices": "1xv5e-8", "chips": 8,
             "state": "QuotaExceeded",
             "detail": "tenant 'batch' at 16/16 chips of v5e-8",
             "position": 0, "wait_s": 12.5, "resumable": True,
             "preemptions": 1},
            {"job": "kubeflow/sweep-3", "tenant": "batch",
             "priority": "low", "slices": "1xv5e-8", "chips": 2.0,
             "state": "Admitted", "detail": "", "position": None,
             "wait_s": None, "resumable": False, "preemptions": 0,
             "members": 4},
            {"job": "kubeflow/serving-lm", "kind": "serving-claim",
             "tenant": "fleet", "priority": "high",
             "slices": "2xv5e-8", "chips": 16, "state": "Admitted",
             "detail": "", "position": None, "wait_s": None,
             "resumable": False, "preemptions": 0},
        ],
        "quotas": [{"tenant": "batch", "slice_type": "v5e-8",
                    "used_chips": 16, "quota_chips": 16}],
        "queue_wait": {"p50": 3.2, "p99": 41.0},
        "counters": {"admitted": 9, "backfilled": 2, "preempted": 1,
                     "resumed": 1},
        "preemptions_in_window": 1,
    }

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            assert self.path == "/queue"
            data = json.dumps(payload).encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        rc = cli.main([
            "queue", "status", "--operator",
            f"http://127.0.0.1:{httpd.server_address[1]}"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "kubeflow/train-a" in out and "Admitted" in out
        assert "MEMBERS" in out
        # KIND column (§5.13): rows without a kind are training jobs
        # from pre-colocation operators; serving claims are labeled.
        assert "KIND" in out
        # The fused member row bills its SHARE of the gang slice and
        # shows the gang width; singletons render "-".
        sweep = next(ln for ln in out.splitlines()
                     if "kubeflow/sweep-3" in ln)
        assert sweep.split()[1] == "train"
        assert sweep.split()[5:7] == ["2", "4"]
        solo = next(ln for ln in out.splitlines()
                    if "kubeflow/train-a" in ln)
        assert solo.split()[5:7] == ["16", "-"]
        claim = next(ln for ln in out.splitlines()
                     if "kubeflow/serving-lm" in ln)
        assert claim.split()[1:4] == ["serving-claim", "fleet", "high"]
        # The resumable queued job is marked: it restarts from its
        # checkpoint, not step 0.
        assert "QuotaExceeded*" in out
        assert "quota batch/v5e-8: 16/16 chips" in out
        assert "preempted=1" in out and "backfilled=2" in out
    finally:
        httpd.shutdown()


def test_fleet_status_renders_endpoint_table(capsys):
    """`kubeflow-tpu fleet status` prints the router's live replica
    table (GET /fleet/endpoints)."""
    import json
    import threading
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    rows = [{"name": "srv-0", "url": "http://10.0.0.5:8000",
             "state": "routable", "tier": "prefill",
             "inflight": 3.0, "queue_depth": 1.0,
             "local_inflight": 0, "breaker_failures": 0,
             "breaker_state": "closed"},
            {"name": "srv-1", "url": "http://10.0.0.6:8000",
             "state": "ejected", "tier": "decode",
             "inflight": 0.0, "queue_depth": 0.0,
             "local_inflight": 0, "breaker_failures": 4,
             "breaker_state": "half_open"}]
    payload = {"endpoints": rows,
               "retry_budget": {"tokens": 7.4, "cap": 10.0},
               "max_replays": 2,
               "pool": {"capacity_chips": 32, "used_chips": 24,
                        "free_chips": 8, "serving_chips": 8,
                        "training_chips": 16}}

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            data = json.dumps(payload).encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        rc = cli.main([
            "fleet", "status", "--router",
            f"http://127.0.0.1:{httpd.server_address[1]}"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "BREAKER" in out
        # Disaggregation tier column (§5.9): the role each replica
        # advertises on /readyz, probed by the router's registry.
        assert "TIER" in out
        assert "srv-0" in out and "routable" in out and "closed" in out
        assert "prefill" in out and "decode" in out
        assert "srv-1" in out and "ejected" in out \
            and "half_open" in out
        # Router-wide failover budget footer.
        assert "retry budget: 7.4/10 tokens" in out
        assert "replay cap 2" in out
        # Combined train/serve pool footer (§5.13) — only reported by
        # colocation-mode routers.
        assert "pool: 24/32 chips used" in out
        assert "(8 serving, 16 training, 8 free)" in out
    finally:
        httpd.shutdown()


def test_fleet_status_accepts_legacy_list_payload(capsys):
    """Routers predating the budget wrapper answer a bare endpoint
    list; the CLI renders it without the footer."""
    import json
    import threading
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    rows = [{"name": "srv-0", "url": "http://10.0.0.5:8000",
             "state": "routable", "inflight": 0.0, "queue_depth": 0.0,
             "local_inflight": 0, "breaker_failures": 0}]

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            data = json.dumps(rows).encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        rc = cli.main([
            "fleet", "status", "--router",
            f"http://127.0.0.1:{httpd.server_address[1]}"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "srv-0" in out
        assert "retry budget" not in out
    finally:
        httpd.shutdown()


def _trace_payload():
    """A two-trace /debug/traces payload: one healthy proxied request
    with the full router->server->engine span chain, one errored."""
    ok_spans = [
        {"trace_id": "aa" * 16, "span_id": "01" * 8, "parent_id": None,
         "name": "router.request", "start_s": 100.0,
         "duration_ms": 25.0, "status": "ok",
         "attrs": {"path": "/model/lm:predict"}},
        {"trace_id": "aa" * 16, "span_id": "02" * 8,
         "parent_id": "01" * 8, "name": "router.forward",
         "start_s": 100.001, "duration_ms": 24.0, "status": "ok",
         "attrs": {"replica": "srv-0"}},
        {"trace_id": "aa" * 16, "span_id": "03" * 8,
         "parent_id": "02" * 8, "name": "server.predict",
         "start_s": 100.002, "duration_ms": 23.0, "status": "ok",
         "attrs": {"model": "lm"}},
        {"trace_id": "aa" * 16, "span_id": "04" * 8,
         "parent_id": "03" * 8, "name": "engine.decode",
         "start_s": 100.01, "duration_ms": 20.0, "status": "ok",
         "attrs": {"tokens": 16}},
    ]
    err_spans = [
        {"trace_id": "bb" * 16, "span_id": "05" * 8, "parent_id": None,
         "name": "router.request", "start_s": 101.0,
         "duration_ms": 120.0, "status": "deadline_exceeded",
         "attrs": {}},
    ]
    return {
        "enabled": True, "capacity": 128, "sample_rate": 0.05,
        "open_traces": 0,
        "traces": [
            {"trace_id": "bb" * 16, "root": "router.request",
             "status": "deadline_exceeded", "retained": "error",
             "duration_ms": 120.0, "spans": err_spans},
            {"trace_id": "aa" * 16, "root": "router.request",
             "status": "ok", "retained": "sampled",
             "duration_ms": 25.0, "spans": ok_spans},
        ],
    }


def _serve_traces(payload):
    import json
    import threading
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            assert self.path == "/debug/traces"
            data = json.dumps(payload).encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd


def test_trace_list_renders_table(capsys):
    """`kubeflow-tpu trace list` prints the retained traces of any
    /debug/traces server (model server, router, or operator)."""
    httpd = _serve_traces(_trace_payload())
    try:
        rc = cli.main([
            "trace", "list", "--target",
            f"http://127.0.0.1:{httpd.server_address[1]}"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "aa" * 16 in out and "bb" * 16 in out
        assert "deadline_exceeded" in out and "error" in out
        assert "router.request" in out
    finally:
        httpd.shutdown()


def test_trace_show_renders_span_tree(capsys):
    """`kubeflow-tpu trace show <id>` renders the span tree with
    durations; a unique id prefix resolves."""
    httpd = _serve_traces(_trace_payload())
    try:
        rc = cli.main([
            "trace", "show", "aaaa", "--target",
            f"http://127.0.0.1:{httpd.server_address[1]}"])
        assert rc == 0
        out = capsys.readouterr().out
        lines = out.splitlines()
        assert lines[0].startswith(f"trace {'aa' * 16}")
        assert "kept_by=sampled" in lines[0]
        # Tree order and nesting: each hop indents under its parent.
        idx = {name: next(i for i, ln in enumerate(lines)
                          if name in ln)
               for name in ("router.request", "router.forward",
                            "server.predict", "engine.decode")}
        assert idx["router.request"] < idx["router.forward"] \
            < idx["server.predict"] < idx["engine.decode"]
        fwd = lines[idx["router.forward"]]
        srv = lines[idx["server.predict"]]
        assert len(srv) - len(srv.lstrip()) \
            > len(fwd) - len(fwd.lstrip())
        assert "replica=srv-0" in out and "tokens=16" in out
        assert "25.0ms" in out
    finally:
        httpd.shutdown()


def test_trace_show_unknown_id_errors(capsys):
    httpd = _serve_traces(_trace_payload())
    try:
        rc = cli.main([
            "trace", "show", "ffff", "--target",
            f"http://127.0.0.1:{httpd.server_address[1]}"])
        assert rc == 1
        assert "no retained trace" in capsys.readouterr().err
    finally:
        httpd.shutdown()


@pytest.fixture(scope="module")
def checkpoint_dir(tmp_path_factory):
    """Three saved steps with the newest one corrupted on disk."""
    import numpy as np

    from kubeflow_tpu.runtime.checkpoint import CheckpointManager

    d = tmp_path_factory.mktemp("ckpt")
    with CheckpointManager(d, max_to_keep=5) as mgr:
        for step in range(3):
            mgr.save(step, {"w": np.arange(4, dtype=np.float32) + step})
    victim = max((p for p in (d / "2").rglob("*") if p.is_file()),
                 key=lambda p: p.stat().st_size)
    victim.write_bytes(victim.read_bytes()[:4])
    return d


def test_checkpoints_list_renders_verdicts(checkpoint_dir, capsys):
    rc = cli.main(["checkpoints", "list", str(checkpoint_dir)])
    out = capsys.readouterr().out
    assert rc == 0
    lines = out.splitlines()
    assert lines[0].split() == ["STEP", "STATUS", "FILES", "SIZE_MB",
                                "DETAIL"]
    by_step = {ln.split()[0]: ln for ln in lines[1:]}
    assert "verified" in by_step["0"]
    assert "verified" in by_step["1"]
    assert "resumes here" in by_step["1"]  # newest verified marked
    assert "corrupt" in by_step["2"]


def test_checkpoints_verify_exit_codes(checkpoint_dir, capsys):
    # Mixed: some steps corrupt but walk-back recovers -> exit 2.
    rc = cli.main(["checkpoints", "verify", str(checkpoint_dir)])
    out = capsys.readouterr().out
    assert rc == 2
    assert "step 2: FAIL" in out
    assert "newest verified step: 1" in out


def test_checkpoints_verify_all_clean(tmp_path, capsys):
    import numpy as np

    from kubeflow_tpu.runtime.checkpoint import CheckpointManager

    with CheckpointManager(tmp_path / "ok") as mgr:
        mgr.save(0, {"w": np.ones(2, np.float32)})
    rc = cli.main(["checkpoints", "verify", str(tmp_path / "ok")])
    out = capsys.readouterr().out
    assert rc == 0 and "step 0: OK" in out


def test_checkpoints_verify_nothing_restorable(tmp_path, capsys):
    import numpy as np

    from kubeflow_tpu.runtime.checkpoint import CheckpointManager

    with CheckpointManager(tmp_path / "bad") as mgr:
        mgr.save(0, {"w": np.ones(2, np.float32)})
    # Manifested but corrupt: walk-back skips it, nothing else exists.
    victim = max((p for p in (tmp_path / "bad" / "0").rglob("*")
                  if p.is_file()), key=lambda p: p.stat().st_size)
    victim.write_bytes(victim.read_bytes()[:4])
    rc = cli.main(["checkpoints", "verify", str(tmp_path / "bad")])
    out = capsys.readouterr().out
    assert rc == 1 and "no restorable steps" in out


def test_checkpoints_legacy_dir_is_a_restore_candidate(tmp_path,
                                                       capsys):
    """A pre-manifest directory is what restore_or_init says it is:
    restorable — the CLI must not tell the operator to throw it away
    (rc 1); it reports legacy candidates and exits 2."""
    import numpy as np

    from kubeflow_tpu.runtime.checkpoint import (
        CheckpointManager,
        manifest_path,
    )

    with CheckpointManager(tmp_path / "old", max_to_keep=5) as mgr:
        for step in range(2):
            mgr.save(step, {"w": np.ones(2, np.float32)})
    for step in range(2):
        manifest_path(tmp_path / "old", step).unlink()
    rc = cli.main(["checkpoints", "verify", str(tmp_path / "old")])
    out = capsys.readouterr().out
    assert rc == 2, out
    assert "legacy" in out and "newest: 1" in out
    rc = cli.main(["checkpoints", "list", str(tmp_path / "old")])
    out = capsys.readouterr().out
    assert rc == 0
    assert "resumes here (legacy, no manifest)" in out


def test_checkpoints_list_empty_dir(tmp_path, capsys):
    rc = cli.main(["checkpoints", "list", str(tmp_path)])
    assert rc == 0
    assert "no checkpoint steps" in capsys.readouterr().out


def test_checkpoints_list_fused_member_layout(tmp_path, capsys):
    """A fused-gang checkpoint root (runtime/hfta.py: per-member
    subdirectories, no steps at the root) renders one verdict table
    per member."""
    import numpy as np

    from kubeflow_tpu.runtime.checkpoint import CheckpointManager

    root = tmp_path / "fused"
    for name in ("m0", "m1"):
        with CheckpointManager(root / name, max_to_keep=3) as mgr:
            mgr.save(4, {"w": np.arange(4, dtype=np.float32)})
    rc = cli.main(["checkpoints", "list", str(root)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "member m0:" in out and "member m1:" in out
    assert out.count("resumes here") == 2
    assert out.count("verified") == 2
