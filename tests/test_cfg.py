"""Golden-edge suite for the analysis CFG + dataflow core.

Each test pins the EDGES the acceptance criteria name: try/finally
with return in both bodies, while/else (break bypasses else), nested
with exception routing, bare-raise re-raise in except handlers, and
generator functions (whose bodies must not inherit definition-site
lock state).  A wrong edge here silently corrupts every flow-sensitive
checker built on top, so the graph shape itself is the contract."""

import ast
import textwrap

from kubeflow_tpu.analysis import analyze_source, cfg


def _graph(src: str, name: str = None):
    tree = ast.parse(textwrap.dedent(src))
    fns = list(cfg.top_level_functions(tree))
    if name is not None:
        fns = [(q, f) for q, f in fns if q == name]
    graph = cfg.build_cfg(fns[0][1])
    assert graph is not None
    return graph


def _node(graph, line, kind=None, exceptional=None):
    hits = [n for n in graph.nodes
            if n.lineno == line
            and (kind is None or n.kind == kind)
            and (exceptional is None or n.exceptional == exceptional)]
    assert hits, f"no node at line {line} kind={kind}"
    return hits[0]


def _reaches(src_node, dst_node) -> bool:
    seen, stack = set(), [src_node]
    while stack:
        node = stack.pop()
        if node is dst_node:
            return True
        if node in seen:
            continue
        seen.add(node)
        stack.extend(succ for succ, _ in node.succs)
    return False


class TestTryFinally:
    SRC = """
    def f():
        try:
            return 1
        finally:
            return 2
    """

    def test_return_routes_through_finally(self):
        graph = _graph(self.SRC)
        ret1 = _node(graph, 4)
        # The try-body return must NOT reach exit directly: its only
        # normal successor is a finally copy whose own return wins.
        assert all(succ.kind == "finally"
                   for succ, kind in ret1.succs if kind == cfg.NORMAL)
        assert _reaches(ret1, graph.exit)

    def test_finally_return_overrides(self):
        graph = _graph(self.SRC)
        # Every path into exit comes from the finally's `return 2`.
        preds = [n for n in graph.nodes
                 if any(s is graph.exit for s, _ in n.succs)]
        assert preds and all(p.lineno == 6 for p in preds)

    def test_exception_copy_also_built(self):
        graph = _graph(self.SRC)
        ret1 = _node(graph, 4)
        exc_targets = [s for s, kind in ret1.succs
                       if kind == cfg.EXCEPTION]
        assert exc_targets and all(t.kind == "finally"
                                   for t in exc_targets)


class TestWhileElse:
    SRC = """
    def g():
        n = 5
        while n:
            if n == 1:
                break
            n -= 1
        else:
            n = 99
        return n
    """

    def test_false_edge_enters_else(self):
        graph = _graph(self.SRC)
        test = _node(graph, 4, kind="loop-test")
        else_stmt = _node(graph, 9)
        assert any(s is else_stmt for s, _ in test.succs)

    def test_break_bypasses_else(self):
        graph = _graph(self.SRC)
        brk = _node(graph, 6)
        else_stmt = _node(graph, 9)
        ret = _node(graph, 10)
        assert not _reaches(brk, else_stmt)
        assert _reaches(brk, ret)

    def test_back_edge(self):
        graph = _graph(self.SRC)
        body_tail = _node(graph, 7)
        test = _node(graph, 4, kind="loop-test")
        assert any(s is test for s, _ in body_tail.succs)


class TestNestedWith:
    SRC = """
    def h(self):
        with self._lock:
            with self._inner_lock:
                work()
        tail()
    """

    def test_exception_unwinds_both_exits(self):
        graph = _graph(self.SRC)
        work = _node(graph, 5)
        inner_exc = _node(graph, 4, kind="with-exit",
                          exceptional=True)
        outer_exc = _node(graph, 3, kind="with-exit",
                          exceptional=True)
        assert any(s is inner_exc and k == cfg.EXCEPTION
                   for s, k in work.succs)
        assert any(s is outer_exc for s, _ in inner_exc.succs)
        assert any(s is graph.raise_exit for s, _ in outer_exc.succs)

    def test_normal_path_exits_in_order(self):
        graph = _graph(self.SRC)
        work = _node(graph, 5)
        inner_ok = _node(graph, 4, kind="with-exit",
                         exceptional=False)
        outer_ok = _node(graph, 3, kind="with-exit",
                         exceptional=False)
        tail = _node(graph, 6)
        assert any(s is inner_ok for s, _ in work.succs)
        assert any(s is outer_ok for s, _ in inner_ok.succs)
        assert any(s is tail for s, _ in outer_ok.succs)

    def test_lock_tokens_scope_to_with_blocks(self):
        graph = _graph(self.SRC)

        def transfer(node, state):
            if node.kind == "with-acquire":
                return state | {node.lineno}
            if node.kind == "with-exit":
                return state - {node.lineno}
            return state

        ins = cfg.fixpoint(graph, frozenset(), transfer)
        assert ins[_node(graph, 5)] == {3, 4}       # both held
        assert ins[_node(graph, 6)] == frozenset()  # both released
        # The exception path released them too (with-exit! nodes ran
        # before raise-exit, and a raising __enter__ never acquired):
        # nothing leaks into the raise state.
        assert ins.get(graph.raise_exit, frozenset()) == frozenset()


class TestBareRaiseReRaise:
    SRC = """
    def k():
        try:
            work()
        except ValueError:
            cleanup()
            raise
        return 1
    """

    def test_protected_body_has_exception_edge(self):
        graph = _graph(self.SRC)
        work = _node(graph, 4)
        assert any(s.kind == "except-dispatch" and k == cfg.EXCEPTION
                   for s, k in work.succs)

    def test_bare_raise_reaches_raise_exit(self):
        graph = _graph(self.SRC)
        re_raise = _node(graph, 7)
        assert any(s is graph.raise_exit and k == cfg.EXCEPTION
                   for s, k in re_raise.succs)

    def test_unmatched_exception_propagates(self):
        graph = _graph(self.SRC)
        dispatch = _node(graph, 3, kind="except-dispatch")
        assert any(s is graph.raise_exit for s, _ in dispatch.succs)

    def test_baseexception_handler_swallows_dispatch_escape(self):
        graph = _graph("""
        def f():
            try:
                work()
            except BaseException:
                recover()
        """)
        dispatch = _node(graph, 3, kind="except-dispatch")
        assert not any(s is graph.raise_exit
                       for s, _ in dispatch.succs)


class TestGenerators:
    def test_is_generator_own_body_only(self):
        tree = ast.parse(textwrap.dedent("""
        def gen():
            yield 1

        def host():
            def inner():
                yield 2
            return inner
        """))
        fns = dict(cfg.top_level_functions(tree))
        assert cfg.is_generator(fns["gen"])
        assert not cfg.is_generator(fns["host"])

    def test_generator_body_not_lock_held(self):
        # The checker-level contract: a generator defined under a
        # lock runs at ITERATION time, after the with exited — its
        # body must not merge the definition site's lock state, while
        # an ordinary nested helper must.
        found = analyze_source(
            '"""m."""\n' + textwrap.dedent("""
            import time


            class C:
                def as_generator(self):
                    with self._lock:
                        def rows():
                            yield 1
                            time.sleep(0.1)
                        self._rows = rows()

                def as_helper(self):
                    with self._lock:
                        def slow():
                            time.sleep(0.1)
                        slow()
            """), rel="kubeflow_tpu/serving/mod.py")
        blocking = [f for f in found
                    if f.check == "blocking-under-lock"]
        assert len(blocking) == 1
        assert "as_helper.slow" in blocking[0].symbol

    def test_yield_keeps_state_within_frame(self):
        # Dataflow still flows THROUGH a yield in the same frame: a
        # lock held across a yield is still held at the next stmt.
        graph = _graph("""
        def gen(self):
            with self._lock:
                yield 1
                after()
            tail()
        """)

        def transfer(node, state):
            if node.kind == "with-enter":
                return state | {"L"}
            if node.kind == "with-exit":
                return state - {"L"}
            return state

        ins = cfg.fixpoint(graph, frozenset(), transfer)
        yield_node = _node(graph, 4)
        assert yield_node.is_yield
        assert ins[_node(graph, 5)] == {"L"}
        assert ins[_node(graph, 6)] == frozenset()


class TestBudget:
    def test_finally_duplication_stays_linear(self):
        # Lazy per-escape-kind finally copies are CACHED: 64 nested
        # try/finally levels must cost O(levels), not 2^levels.
        depth = 64
        body = "x = 1\n"
        for _ in range(depth):
            body = ("try:\n"
                    + textwrap.indent(body, "    ")
                    + "finally:\n    y = 2\n")
        src = "def f():\n" + textwrap.indent(body, "    ")
        fn = ast.parse(src).body[0]
        graph = cfg.build_cfg(fn)
        assert graph is not None
        assert len(graph.nodes) < 20 * depth

    def test_oversized_function_skipped_not_mis_analyzed(self):
        # Past the node budget build_cfg must give up loudly (None),
        # never truncate the graph.
        src = "def f():\n" + "    x = 1\n" * (cfg.MAX_NODES + 10)
        fn = ast.parse(src).body[0]
        assert cfg.build_cfg(fn) is None
