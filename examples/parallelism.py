"""A runnable tour of every parallelism family on one model.

The reference expressed parallelism as replica counts wired by TF_CONFIG
or MPI hostfiles (SURVEY.md §2.3); here each family is a mesh shape, and
the SAME flagship Transformer trains through all of them — this script
runs the whole ladder on a virtual 8-device CPU slice in a few minutes:

    JAX_PLATFORMS=cpu \
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python examples/parallelism.py

On a real slice, drop the env vars and scale the sizes; a TPUJob
declares the same axes in `spec.mesh` (docs/user_guide.md §7).
Executed in CI by tests/test_examples.py.
"""

from __future__ import annotations

import os
import sys


def main() -> int:
    # Same opt-in gate as quickstart.py: pin the virtual CPU slice
    # unless the user explicitly asks for real hardware (probing
    # jax.default_backend() here would initialize — and possibly fail
    # on — whatever plugin the environment pre-selected).
    if not os.environ.get("KFT_PARALLELISM_TPU"):
        os.environ["JAX_PLATFORMS"] = "cpu"
        if "xla_force_host_platform_device_count" not in \
                os.environ.get("XLA_FLAGS", ""):
            os.environ["XLA_FLAGS"] = (
                os.environ.get("XLA_FLAGS", "")
                + " --xla_force_host_platform_device_count=8").strip()
    import jax

    import jax.numpy as jnp
    import numpy as np
    import optax

    from kubeflow_tpu.models.transformer import TransformerConfig, lm_task
    from kubeflow_tpu.parallel import MeshSpec
    from kubeflow_tpu.runtime.metrics import MetricsLogger
    from kubeflow_tpu.runtime.train import Trainer

    base = dict(
        vocab_size=128, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=128, head_dim=16, max_seq_len=64, dtype=jnp.bfloat16,
    )
    # (name, mesh, config overrides) — one row per family.  Sizes are
    # sized for 8 devices; each mesh trains 2 steps of the real model.
    ladder = [
        ("data-parallel", MeshSpec(data=8), {}),
        ("fsdp (ZeRO-3)", MeshSpec(data=2, fsdp=4), {}),
        ("tensor-parallel", MeshSpec(data=4, tensor=2), {}),
        ("sequence-parallel (ring attention)",
         MeshSpec(data=4, sequence=2), {"attention": "ring"}),
        ("expert-parallel (MoE)",
         MeshSpec(data=4, expert=2), {"moe_experts": 4}),
        ("pipeline-parallel (GPipe)",
         MeshSpec(data=4, pipeline=2),
         {"pipeline_microbatches": 4, "attention": "dot"}),
        # The composed finale: ring attention + MoE + GPipe in ONE
        # program over a pipeline x sequence x expert mesh — the
        # combinations a >1-slice MoE long-context job wants (the r4
        # composition walls, lifted in r5).
        ("pp x sp x ep composed (ring + MoE through GPipe)",
         MeshSpec(pipeline=2, sequence=2, expert=2),
         {"pipeline_microbatches": 2, "attention": "ring",
          "moe_experts": 2}),
    ]
    rng = np.random.RandomState(0)
    devnull = open(os.devnull, "w")
    for name, spec, overrides in ladder:
        mesh = spec.build()
        cfg = TransformerConfig(**{**base, **overrides})
        init_fn, loss_fn = lm_task(cfg, mesh=mesh)
        trainer = Trainer(
            init_fn=init_fn, loss_fn=loss_fn, tx=optax.adamw(1e-3),
            mesh=mesh,
            metrics=MetricsLogger(stream=devnull),
        )
        batch = max(8, mesh.shape["data"] * mesh.shape["fsdp"] * 2)
        tokens = rng.randint(0, cfg.vocab_size,
                             size=(batch, 32)).astype(np.int32)

        def data(tokens=tokens):
            while True:
                yield {"tokens": tokens}

        trainer.fit(data(), num_steps=2, examples_per_step=batch,
                    log_every=0)
        loss = trainer.last_metrics["loss"]
        axes = {a: s for a, s in mesh.shape.items() if s > 1}
        print(f"{name:40s} mesh={axes}  loss={loss:.3f}")
    devnull.close()
    print("parallelism tour complete: every family trained the real "
          "Transformer")
    return 0


if __name__ == "__main__":
    sys.exit(main())
