"""End-to-end quickstart: train -> checkpoint -> export -> serve -> query.

Runs anywhere in under a minute — on a laptop it uses the virtual CPU
slice, on a TPU host the real chip:

    python examples/quickstart.py

What it shows, in order (the same surfaces docs/user_guide.md walks
through, as one executable script):

  1. a tiny Transformer LM trained for a few steps with ``Trainer.fit``
     on a {data, fsdp} mesh (the full SPMD loop: sharded params,
     compiled psum, metrics);
  2. an orbax checkpoint written and restored (``restore_or_init``);
  3. the model exported as a versioned serving artifact with the
     ``lm_generate`` loader (KV-cache decode);
  4. the first-party model server loading it and answering a REST
     ``:predict`` call over HTTP — the reference's wire contract.

The reference's equivalent journey spanned ks prototypes, a TFJob CR,
an external model server, and a proxy (user_guide.md sections 4-5 of
/root/reference); here it is one python file against one package.
"""

from __future__ import annotations

import json
import os
import tempfile
import urllib.request


def main() -> int:
    # Fake-slice setup must happen before jax initializes (harmless on a
    # real TPU host: set KFT_QUICKSTART_TPU=1 to use the local chip).
    if not os.environ.get("KFT_QUICKSTART_TPU"):
        os.environ["JAX_PLATFORMS"] = "cpu"
        flags = os.environ.get("XLA_FLAGS", "")
        if "host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from kubeflow_tpu.models.transformer import TransformerConfig, lm_task
    from kubeflow_tpu.parallel import MeshSpec
    from kubeflow_tpu.runtime.checkpoint import CheckpointManager
    from kubeflow_tpu.runtime.train import Trainer
    from kubeflow_tpu.serving.export import export
    from kubeflow_tpu.serving.http import make_http_server
    from kubeflow_tpu.serving.model_server import ModelServer

    overrides = dict(
        vocab_size=64, d_model=32, n_layers=2, n_heads=4, n_kv_heads=4,
        d_ff=64, head_dim=8, max_seq_len=64,
    )
    cfg = TransformerConfig(dtype=jnp.float32, **overrides)

    # -- 1. train on a data x fsdp mesh ---------------------------------
    devices = jax.devices()
    mesh = MeshSpec(data=max(1, len(devices) // 2),
                    fsdp=min(2, len(devices))).build(devices)
    init_fn, loss_fn = lm_task(cfg, mesh=mesh)

    workdir = tempfile.mkdtemp(prefix="kft-quickstart-")
    ckpts = CheckpointManager(f"{workdir}/ckpt")
    trainer = Trainer(
        init_fn=init_fn, loss_fn=loss_fn, tx=optax.adam(3e-3), mesh=mesh,
        checkpoints=ckpts,
    )
    state = trainer.create_state()

    rng = np.random.RandomState(0)

    def batches():
        while True:
            # A learnable stream: each row counts up from a random start.
            start = rng.randint(0, 32, size=(8, 1))
            yield {"tokens": ((start + np.arange(16)) % 32)
                   .astype(np.int32)}

    state = trainer.fit(batches(), num_steps=30, state=state,
                        examples_per_step=8, log_every=10)
    loss = trainer.last_metrics["loss"]
    print(f"[1] trained 30 steps on {mesh.shape}, loss={loss:.3f}")

    # -- 2. checkpoint round trip ---------------------------------------
    ckpts.save(int(state.step), state, force=True)
    ckpts.wait()
    restored, start_step = ckpts.restore_or_init(state)
    # The resume contract: training would continue at the NEXT step.
    assert start_step == int(state.step) + 1
    print(f"[2] checkpointed at step {int(state.step)}; "
          f"resume would start at {start_step}")

    # -- 3. export for serving ------------------------------------------
    export(
        f"{workdir}/models/lm", 1, {"params": state.params},
        loader="kubeflow_tpu.serving.loaders:lm_generate",
        config={"model": {**overrides, "dtype": "float32"},
                "max_new_tokens": 8, "temperature": 0.0},
    )
    print(f"[3] exported version 1 under {workdir}/models/lm")

    # -- 4. serve + query over REST -------------------------------------
    server = ModelServer()
    server.add_model("lm", f"{workdir}/models/lm")
    httpd, _ = make_http_server(server, port=0, host="127.0.0.1")
    port = httpd.server_address[1]
    prompt = [[3, 1, 4, 1, 5]]
    body = json.dumps({"instances": [{"tokens": prompt[0]}]}).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/model/lm:predict", data=body,
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=60) as resp:
        out = json.loads(resp.read())
    completion = out["predictions"][0]["tokens"]
    httpd.shutdown()
    assert len(completion) == len(prompt[0]) + 8
    print(f"[4] REST :predict -> {completion}")
    print("quickstart OK")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
