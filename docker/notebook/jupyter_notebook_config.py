# Default notebook config seeded into a fresh PVC home by
# kubeflow_tpu/tools/notebook_entry.py (heir of the reference's
# jupyter_notebook_config.py shipped in
# components/tensorflow-notebook-image/).
c = get_config()  # noqa: F821
c.ServerApp.open_browser = False
c.ServerApp.allow_origin = "*"
# Notebooks live under the PVC-backed work dir so they survive restarts.
c.ServerApp.root_dir = "work"
