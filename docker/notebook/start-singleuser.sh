#!/bin/bash
# JupyterHub single-user entry — thin exec wrapper; the PVC-home seeding
# and arg assembly live in kubeflow_tpu/tools/notebook_entry.py (heir of
# the reference's pvc-check.sh + start-singleuser.sh + start.sh trio,
# components/tensorflow-notebook-image/), where they are unit-tested.
set -e
exec python -m kubeflow_tpu.tools.notebook_entry "$@"
