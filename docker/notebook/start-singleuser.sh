#!/bin/bash
# JupyterHub single-user entry — heir of the reference's
# start-singleuser.sh (components/tensorflow-notebook-image/): ensure the
# PVC-mounted home is usable, then exec the hub-managed server.
set -e

if [ ! -w "$HOME" ]; then
  echo "warning: $HOME not writable (PVC mount problem?)" >&2
fi

exec jupyterhub-singleuser --ip=0.0.0.0 "$@"
